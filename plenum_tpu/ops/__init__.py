"""TPU-accelerated batch primitives (JAX/XLA).

The framework's hot data paths — merkle SHA-256 hashing, ed25519 signature
verification, BLS12-381 aggregation — are expressed as pure batched JAX
functions in this package, dispatched from the host-side consensus loop
behind pluggable provider seams (SURVEY.md §2.9).
"""
import os


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= n — the shared bucket-rounding rule for
    batch padding (ops/mesh.py) and tree capacity (ops/merkle.py)."""
    p = 1
    while p < n:
        p *= 2
    return p


def enable_persistent_compilation_cache(path: str = None) -> str:
    """Point XLA's persistent compilation cache at `path` (default:
    <repo>/.jax_cache). The big verify buckets take 30-110s to compile;
    with the cache, every process after the first loads them in
    milliseconds. Must use jax.config (the JAX_COMPILATION_CACHE_DIR
    env var alone does not activate the cache on all backends)."""
    import jax
    if path is None:
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path
