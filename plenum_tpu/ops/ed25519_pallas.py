"""Pallas TPU kernel: the ENTIRE batched ed25519 verification in one
kernel launch.

Why: the XLA expression of the verify (ops/ed25519_jax.py) is a chain of
~3,500 field multiplies, each lowered around a [B,400]x[400,42] int32
matmul. The matmuls are fusion barriers, so every fmul round-trips its
operands through HBM — the kernel is bandwidth-bound at ~100us per fmul
(B=8192) and the compiled executable is enormous (30-110s compiles).

Here the whole computation lives in VMEM: a field element is 20 limb
*registers* of shape [BLOCK_R,128] (BLOCK_R x 128 = one batch block of
BLOCK signatures; see BLOCK_R below), the 20x20 limb convolution is unrolled
multiply-adds on those tiles, and the only HBM traffic per block is the
kernel's inputs (~700KB) and the ok-bit output. Same radix-2^13 limb
discipline, carry schedule, windowed double-scalar multiplication, and
niels-form tables as the XLA kernel — outputs are bit-identical (tests
cross-check both against the RFC 8032 scalar implementation).

Reference for the math: ops/ed25519_jax.py (which cites RFC 8032 and the
ref10 pow22523 chain); this file only re-schedules it for the VPU.
"""
import functools

import numpy as np
import jax.numpy as jnp

from plenum_tpu.ops import ed25519_jax as edj

NLIMB = edj.NLIMB
RADIX = edj.RADIX
MASK = edj.MASK

# scalar (python-int) constants: folded into the kernel as immediates
_SPREAD = [int(v) for v in edj._SPREAD_8P]
_ONE = [int(v) for v in edj._ONE_L]
_D = [int(v) for v in edj._D_L]
_TWOD = [int(v) for v in edj._TWOD_L]
_SQRT_M1 = [int(v) for v in edj._SQRT_M1_L]
_NB_SUB = np.asarray(edj._NB_SUB)      # [16, 20] int32 (constant table)
_NB_ADD = np.asarray(edj._NB_ADD)
_NB_T2D = np.asarray(edj._NB_T2D)

BLOCK_R = 32         # sublanes per batch block (32x128 = 4096 sigs/block;
                     # needs the raised vmem limit below — the window
                     # table, 16 entries x 80 limb-tiles, dominates)
BLOCK_L = 128        # lanes
BLOCK = BLOCK_R * BLOCK_L
VMEM_LIMIT_BYTES = 100 * 1024 * 1024   # v5e has 128MB VMEM; the default
                                       # 16MB scoped limit is what an
                                       # R=32 working set (~26MB) trips


# ------------------------------------------------- field ops on limb lists
# A field element is a list of NLIMB [8,128] int32 arrays. All helpers
# mirror ops/ed25519_jax.py exactly (same bounds discipline), just in
# limb-major "structure of registers" form.

def _finalize20(out):
    """Normalize 20 columns to the limb invariant (edj._finalize20):
    2x carry-wrap, fold bits >= 255 (x19), 1x carry-wrap. Applied after
    every add/sub exactly as the XLA kernel does — keeping every field
    element < ~2^255.2 is what makes fcanon's single-subtract zero test
    sound AND keeps the convolution column sums inside int32."""
    for _ in range(2):
        nxt = [oi & MASK for oi in out]
        for k in range(NLIMB - 1):
            nxt[k + 1] = nxt[k + 1] + (out[k] >> RADIX)
        nxt[0] = nxt[0] + (out[NLIMB - 1] >> RADIX) * 608
        out = nxt
    top = out[NLIMB - 1] >> 8
    out[0] = out[0] + top * 19
    out[NLIMB - 1] = out[NLIMB - 1] - (top << 8)
    nxt = [oi & MASK for oi in out]
    for k in range(NLIMB - 1):
        nxt[k + 1] = nxt[k + 1] + (out[k] >> RADIX)
    nxt[0] = nxt[0] + (out[NLIMB - 1] >> RADIX) * 608
    return nxt


def _fadd(a, b):
    return _finalize20([x + y for x, y in zip(a, b)])


def _fsub(a, b):
    return _finalize20([x + s - y for x, y, s in zip(a, b, _SPREAD)])


def _fneg(a):
    return _finalize20([s - x for x, s in zip(a, _SPREAD)])


def _conv_carry_fold(c):
    """Shared tail of mul/square: 3 carry rounds on 42 columns, fold
    cols >= 20 (x608 per 2^260 wrap), finalize to the limb invariant."""
    zero = jnp.zeros_like(c[0])
    c = c + [zero] * (42 - len(c))
    for _ in range(3):
        nxt = [ci & MASK for ci in c]
        for k in range(41):
            nxt[k + 1] = nxt[k + 1] + (c[k] >> RADIX)
        c = nxt
    out = [c[k] + c[20 + k] * 608 for k in range(20)]
    out[0] = out[0] + c[40] * (608 * 608)
    out[1] = out[1] + c[41] * (608 * 608)
    return _finalize20(out)


def _fmul(a, b):
    c = []
    for k in range(2 * NLIMB - 1):
        terms = [a[i] * b[k - i]
                 for i in range(max(0, k - NLIMB + 1), min(NLIMB, k + 1))]
        acc = terms[0]
        for t in terms[1:]:
            acc = acc + t
        c.append(acc)
    return _conv_carry_fold(c)


def _fmul_const(a, const_limbs):
    """a x compile-time constant (list of python ints); zero limbs of
    the constant drop their partial products entirely."""
    c = []
    for k in range(2 * NLIMB - 1):
        acc = None
        for i in range(max(0, k - NLIMB + 1), min(NLIMB, k + 1)):
            cv = const_limbs[k - i]
            if cv == 0:
                continue
            term = a[i] * cv
            acc = term if acc is None else acc + term
        if acc is None:
            acc = jnp.zeros_like(a[0])
        c.append(acc)
    return _conv_carry_fold(c)


def _fsq(a):
    """Squaring: symmetric convolution, ~half the multiplies."""
    c = []
    for k in range(2 * NLIMB - 1):
        acc = None
        lo = max(0, k - NLIMB + 1)
        hi = min(NLIMB - 1, k)
        i = lo
        while i < k - i:
            term = a[i] * a[k - i]
            term = term + term
            acc = term if acc is None else acc + term
            i += 1
        if 2 * i == k:
            term = a[i] * a[i]
            acc = term if acc is None else acc + term
        c.append(acc)
    return _conv_carry_fold(c)


def _fcanon(x):
    """Canonical representative in [0, p) (edj.fcanon, list form)."""
    t = list(x)
    t[0] = t[0] + 19
    for k in range(NLIMB - 1):
        cr = t[k] >> RADIX
        t[k] = t[k] - (cr << RADIX)
        t[k + 1] = t[k + 1] + cr
    q = t[NLIMB - 1] >> 8
    r = list(x)
    r[0] = r[0] + q * 19
    r[NLIMB - 1] = r[NLIMB - 1] - (q << 8)
    for k in range(NLIMB - 1):
        cr = r[k] >> RADIX
        r[k] = r[k] - (cr << RADIX)
        r[k + 1] = r[k + 1] + cr
    return r


def _fiszero(x):
    xc = _fcanon(x)
    acc = xc[0] == 0
    for limb in xc[1:]:
        acc = acc & (limb == 0)
    return acc


def _feq(a, b):
    return _fiszero(_fsub(a, b))


def _where_fe(mask, a, b):
    return [jnp.where(mask, x, y) for x, y in zip(a, b)]


def _sqn(x, n):
    import jax.lax as lax
    if n <= 4:
        return functools.reduce(lambda acc, _: _fsq(acc), range(n), x)

    def body(i, acc):
        return tuple(_fsq(list(acc)))
    return list(lax.fori_loop(0, n, body, tuple(x)))


def _pow_p58(x):
    """x^((p-5)/8), ref10 pow22523 chain (as edj.pow_p58)."""
    z2 = _fsq(x)
    z9 = _fmul(_sqn(z2, 2), x)
    z11 = _fmul(z9, z2)
    z22 = _fsq(z11)
    z_5_0 = _fmul(z22, z9)
    z_10_0 = _fmul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = _fmul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = _fmul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = _fmul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = _fmul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = _fmul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = _fmul(_sqn(z_200_0, 50), z_50_0)
    return _fmul(_sqn(z_250_0, 2), x)


def _const_fe(value_limbs, like):
    return [jnp.full_like(like, v) for v in value_limbs]


def _decompress(y, sign):
    """(x, ok) from y limbs + sign bit (edj.decompress, list form)."""
    yy = _fsq(y)
    one = _const_fe(_ONE, y[0])
    u = _fsub(yy, one)
    v = _fadd(_fmul_const(yy, _D), one)
    v2 = _fsq(v)
    v3 = _fmul(v2, v)
    v7 = _fmul(_fsq(v3), v)
    x = _fmul(_fmul(u, v3), _pow_p58(_fmul(u, v7)))
    vxx = _fmul(v, _fsq(x))
    is_root = _feq(vxx, u)
    is_neg_root = _fiszero(_fadd(vxx, u))
    x = _where_fe(is_neg_root & ~is_root, _fmul_const(x, _SQRT_M1), x)
    ok = is_root | is_neg_root
    xc = _fcanon(x)
    x_zero = xc[0] == 0
    for limb in xc[1:]:
        x_zero = x_zero & (limb == 0)
    ok = ok & ~(x_zero & (sign == 1))
    parity = xc[0] & 1
    x = _where_fe(parity != sign, _fneg(xc), xc)
    return x, ok


# -------------------------------------------------------------- point ops

def _pt_double(X, Y, Z, T):
    A = _fsq(X)
    B = _fsq(Y)
    Zs = _fsq(Z)
    C = _fadd(Zs, Zs)
    E = _fsub(_fsub(_fsq(_fadd(X, Y)), A), B)
    G = _fsub(B, A)
    F = _fsub(G, C)
    H = _fsub(_fneg(A), B)
    return _fmul(E, F), _fmul(G, H), _fmul(F, G), _fmul(E, H)


def _pt_add(X1, Y1, Z1, T1, X2, Y2, Z2, T2):
    A = _fmul(_fsub(Y1, X1), _fsub(Y2, X2))
    B = _fmul(_fadd(Y1, X1), _fadd(Y2, X2))
    C = _fmul(_fmul_const(T1, _TWOD), T2)
    ZZ = _fmul(Z1, Z2)
    Dv = _fadd(ZZ, ZZ)
    E = _fsub(B, A)
    F = _fsub(Dv, C)
    G = _fadd(Dv, C)
    H = _fadd(B, A)
    return _fmul(E, F), _fmul(G, H), _fmul(F, G), _fmul(E, H)


def _pt_add_prescaled(X1, Y1, Z1, T1, X2, Y2, Z2, T2_2d):
    A = _fmul(_fsub(Y1, X1), _fsub(Y2, X2))
    B = _fmul(_fadd(Y1, X1), _fadd(Y2, X2))
    C = _fmul(T1, T2_2d)
    Dv = _fmul(_fadd(Z1, Z1), Z2)
    E = _fsub(B, A)
    F = _fsub(Dv, C)
    G = _fadd(Dv, C)
    H = _fadd(B, A)
    return _fmul(E, F), _fmul(G, H), _fmul(F, G), _fmul(E, H)


def _pt_add_niels_const(X1, Y1, Z1, T1, n_sub, n_add, n_t2d):
    """Mixed add with a CONSTANT niels point, each coord a python-int
    limb list (selected per-lane before the call)."""
    A = _fmul(_fsub(Y1, X1), n_sub)
    B = _fmul(_fadd(Y1, X1), n_add)
    C = _fmul(T1, n_t2d)
    Dv = _fadd(Z1, Z1)
    E = _fsub(B, A)
    F = _fsub(Dv, C)
    G = _fadd(Dv, C)
    H = _fadd(B, A)
    return _fmul(E, F), _fmul(G, H), _fmul(F, G), _fmul(E, H)


def _select_const_table(dig, table):
    """Per-lane select from a [16, 20] CONSTANT table: limb k becomes
    sum_d (dig==d) * table[d,k] with the scalars folded as immediates."""
    masks = [(dig == d) for d in range(16)]
    out = []
    for k in range(NLIMB):
        acc = None
        for d in range(16):
            v = int(table[d, k])
            if v == 0:
                continue
            term = jnp.where(masks[d], v, 0)
            acc = term if acc is None else acc + term
        out.append(acc if acc is not None else jnp.zeros_like(dig))
    return out


def _select_batched_table(dig, entries):
    """Per-lane select of one of 16 runtime points (tuples of limb
    lists): tree of where-selects on the 4 digit bits."""
    b0 = (dig & 1) == 1
    b1 = (dig & 2) == 2
    b2 = (dig & 4) == 4
    b3 = (dig & 8) == 8

    def sel(mask, pa, pb):
        return tuple([jnp.where(mask, x, y) for x, y in zip(ca, cb)]
                     for ca, cb in zip(pa, pb))

    lvl1 = [sel(b0, entries[2 * i + 1], entries[2 * i]) for i in range(8)]
    lvl2 = [sel(b1, lvl1[2 * i + 1], lvl1[2 * i]) for i in range(4)]
    lvl3 = [sel(b2, lvl2[2 * i + 1], lvl2[2 * i]) for i in range(2)]
    return sel(b3, lvl3[1], lvl3[0])


# ------------------------------------------------------------- the kernel

def _verify_kernel_pallas(ay_ref, asign_ref, ry_ref, rsign_ref,
                          sd_ref, kd_ref, ok_ref):
    import jax.lax as lax
    from jax.experimental import pallas as pl   # noqa: F401 (pl.ds below)

    ay = [ay_ref[i] for i in range(NLIMB)]
    ry = [ry_ref[i] for i in range(NLIMB)]
    asign = asign_ref[0]
    rsign = rsign_ref[0]

    ax, ok_a = _decompress(ay, asign)
    rx, ok_r = _decompress(ry, rsign)

    one = _const_fe(_ONE, ay[0])
    zero = _const_fe([0] * NLIMB, ay[0])

    # per-signature table: d * (-A) for d = 0..15, extended coords
    nax = _fneg(ax)
    na = (nax, ay, one, _fmul(nax, ay))
    tab = [(zero, one, one, zero), na]
    for d in range(2, 16):
        if d % 2 == 0:
            tab.append(_pt_double(*tab[d // 2]))
        else:
            tab.append(_pt_add(*tab[d - 1], *na))
    # pre-scale T by 2d so the loop add costs 8 muls
    tab = [(X, Y, Z, _fmul_const(T, _TWOD)) for (X, Y, Z, T) in tab]

    def body(i, st):
        w = 63 - i
        X, Y, Z, T = [list(c) for c in st]
        for _ in range(4):
            X, Y, Z, T = _pt_double(X, Y, Z, T)
        s_dig = sd_ref[pl.ds(w, 1)][0]
        k_dig = kd_ref[pl.ds(w, 1)][0]
        n_sub = _select_const_table(s_dig, _NB_SUB)
        n_add = _select_const_table(s_dig, _NB_ADD)
        n_t2d = _select_const_table(s_dig, _NB_T2D)
        X, Y, Z, T = _pt_add_niels_const(X, Y, Z, T, n_sub, n_add, n_t2d)
        x2, y2, z2, t2d2 = _select_batched_table(k_dig, tab)
        X, Y, Z, T = _pt_add_prescaled(X, Y, Z, T, x2, y2, z2, t2d2)
        return tuple(tuple(c) for c in (X, Y, Z, T))

    ident = tuple(tuple(c) for c in (zero, one, one, zero))
    X, Y, Z, _T = lax.fori_loop(0, 64, body, ident)

    ok_x = _fiszero(_fsub(_fmul(rx, list(Z)), list(X)))
    ok_y = _fiszero(_fsub(_fmul(ry, list(Z)), list(Y)))
    ok = ok_a & ok_r & ok_x & ok_y
    ok_ref[0] = ok.astype(jnp.int32)


@functools.lru_cache(maxsize=None)
def _build_verify(n_blocks: int, interpret: bool = False):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n_blocks,)
    fe_spec = pl.BlockSpec((NLIMB, BLOCK_R, BLOCK_L),
                           lambda i: (0, i, 0))
    sign_spec = pl.BlockSpec((1, BLOCK_R, BLOCK_L), lambda i: (0, i, 0))
    dig_spec = pl.BlockSpec((64, BLOCK_R, BLOCK_L), lambda i: (0, i, 0))
    nb8 = n_blocks * BLOCK_R

    def to_blocks(x_bt):
        """[B, K] int32 → [K, nb8, 128] (limb-major, 8x128 tiles)."""
        return jnp.transpose(x_bt, (1, 0)).reshape(
            x_bt.shape[1], nb8, BLOCK_L)

    # ONE jitted function does digit extraction + relayout + the pallas
    # call: each un-jitted jnp op would otherwise pay its own dispatch
    # round trip (~25ms through a tunneled device — 8 ops cost more
    # than the kernel itself)
    def run(ay, asign, ry, rsign, s_words, k_words):
        sd = to_blocks(edj._digits4(s_words))
        kd = to_blocks(edj._digits4(k_words))
        out = pl.pallas_call(
            _verify_kernel_pallas,
            grid=grid,
            in_specs=[fe_spec, sign_spec, fe_spec, sign_spec,
                      dig_spec, dig_spec],
            out_specs=sign_spec,
            out_shape=jax.ShapeDtypeStruct(
                (1, nb8, BLOCK_L), jnp.int32),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=VMEM_LIMIT_BYTES),
            interpret=interpret,
        )(to_blocks(ay), to_blocks(asign[:, None].astype(jnp.int32)),
          to_blocks(ry), to_blocks(rsign[:, None].astype(jnp.int32)),
          sd, kd)
        return out.reshape(nb8 * BLOCK_L) != 0

    return jax.jit(run)


def verify_kernel(ay, asign, ry, rsign, s_words, k_words,
                  interpret: bool = False):
    """Drop-in equivalent of edj._verify_kernel (same arguments, same
    bool[B] result) running the single-launch Pallas kernel. Batch is
    padded to a BLOCK multiple internally."""

    B = int(ay.shape[0])
    pad = (-B) % BLOCK
    if pad:
        def padb(x):
            widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, widths)
        ay, asign, ry, rsign, s_words, k_words = (
            padb(x) for x in (ay, asign, ry, rsign, s_words, k_words))
    total = B + pad
    ok = _build_verify(total // BLOCK, interpret)(
        ay, asign, ry, rsign, s_words, k_words)
    return ok[:B]
