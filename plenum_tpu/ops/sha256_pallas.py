"""Pallas TPU kernel: batched SHA-256 compression entirely in VMEM.

Why: the XLA expression of the compression (ops/sha256._sha256_blocks)
lowers op-by-op — every one of the ~1600 uint32 ops per compression
materializes a batch-wide temp, so the kernel round-trips its working
set through HBM once per op and the tree-hash workload is bandwidth-
bound (the MTU and zkSpeed hash accelerators in PAPERS.md win exactly
by fusing the message schedule and the rounds into one unit). Here the
whole compression lives in VMEM: the 8 state words and the rolling
16-word schedule window are [BLOCK_R, 128] uint32 register tiles, the
64 rounds are fully unrolled (rotations become static shift/or pairs
on the VPU), and the only HBM traffic per grid step is the kernel's
padded message words in and the 8 digest words out.

Layout mirrors ops/ed25519_pallas.py: callers keep the XLA kernel's
[B, nblocks, 16] uint32 convention; `sha256_blocks` relayouts to
word-major [nblocks*16, nb8, 128] tiles, pads the batch to a BLOCK
multiple, and runs one grid step per BLOCK messages. Outputs are
byte-identical to ops/sha256._sha256_blocks (tests cross-check both
against hashlib), so the merkle/ledger seams can route here above a
batch threshold with no caller changes.

Availability follows the ed25519 pattern: ONE shared probe
(ops/mesh.pallas_backend_enabled, env PLENUM_TPU_SHA256_BACKEND) and
interpret-mode execution for CPU tests, so tier-1 exercises the kernel
byte-for-byte on hosts without a TPU.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from plenum_tpu.ops.sha256 import _IV, _K

PALLAS_ENV = "PLENUM_TPU_SHA256_BACKEND"

BLOCK_R = 8          # sublanes per batch block (8x128 = 1024 msgs)
BLOCK_L = 128        # lanes
BLOCK = BLOCK_R * BLOCK_L


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_tiles(state, w):
    """One SHA-256 compression on [BLOCK_R, BLOCK_L] uint32 tiles.
    state: list of 8 tiles; w: list of 16 message-word tiles. Rounds
    fully unrolled; the schedule extends the same list (w[t] for
    t >= 16 is computed once and stays a VMEM register)."""
    a, b, c, d, e, f, g, h = state
    w = list(w)
    for t in range(64):
        if t >= 16:
            w15 = w[t - 15]
            w2 = w[t - 2]
            sig0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
            sig1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
            w.append(w[t - 16] + sig0 + w[t - 7] + sig1)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + jnp.uint32(int(_K[t])) + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    return [s + v for s, v in zip(state, (a, b, c, d, e, f, g, h))]


def _sha256_kernel(nblocks: int):
    """Kernel body for a fixed (static) block count per message."""

    def kernel(w_ref, nv_ref, out_ref):
        nv = nv_ref[0]
        state = [jnp.full((BLOCK_R, BLOCK_L), jnp.uint32(int(v)))
                 for v in _IV]
        for blk in range(nblocks):
            w = [w_ref[blk * 16 + j] for j in range(16)]
            new = _compress_tiles(state, w)
            # ragged block counts: rows whose message ended keep their
            # state (blk 0 is always valid — nvalid >= 1 by padding)
            if blk == 0:
                state = new
            else:
                mask = jnp.int32(blk) < nv
                state = [jnp.where(mask, n_, s_)
                         for n_, s_ in zip(new, state)]
        for j in range(8):
            out_ref[j] = state[j]

    return kernel


@functools.lru_cache(maxsize=None)
def _build_sha256(n_grid: int, nblocks: int, interpret: bool = False):
    from jax.experimental import pallas as pl

    nb8 = n_grid * BLOCK_R
    word_spec = pl.BlockSpec((nblocks * 16, BLOCK_R, BLOCK_L),
                             lambda i: (0, i, 0))
    nv_spec = pl.BlockSpec((1, BLOCK_R, BLOCK_L), lambda i: (0, i, 0))
    out_spec = pl.BlockSpec((8, BLOCK_R, BLOCK_L), lambda i: (0, i, 0))

    def to_blocks(x_bt):
        """[B, K] → [K, nb8, 128] (word-major, 8x128 tiles)."""
        return jnp.transpose(x_bt, (1, 0)).reshape(
            x_bt.shape[1], nb8, BLOCK_L)

    # ONE jitted function does relayout + the pallas call + un-layout,
    # so callers pay a single dispatch (ed25519_pallas precedent)
    def run(words, nvalid):
        wb = to_blocks(words.reshape(words.shape[0], nblocks * 16))
        nvb = to_blocks(nvalid[:, None])
        out = pl.pallas_call(
            _sha256_kernel(nblocks),
            grid=(n_grid,),
            in_specs=[word_spec, nv_spec],
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((8, nb8, BLOCK_L),
                                           jnp.uint32),
            interpret=interpret,
        )(wb, nvb)
        return jnp.transpose(out.reshape(8, nb8 * BLOCK_L), (1, 0))

    return jax.jit(run)


def sha256_blocks(words, nvalid, nblocks: int, interpret: bool = False):
    """Drop-in equivalent of ops/sha256._sha256_blocks (same
    [B, nblocks, 16] u32 + [B] i32 arguments, same [B, 8] u32 digests)
    running the single-launch Pallas kernel. The batch is padded to a
    BLOCK multiple internally (pad rows hash garbage that the slice
    drops). Traceable: callers may invoke it inside their own jit
    (ops/merkle's fused build does)."""
    B = int(words.shape[0])
    pad = (-B) % BLOCK
    if pad:
        words = jnp.pad(words, ((0, pad), (0, 0), (0, 0)))
        nvalid = jnp.pad(nvalid, (0, pad), constant_values=1)
    dig = _build_sha256((B + pad) // BLOCK, nblocks, interpret)(
        words, nvalid.astype(jnp.int32))
    return dig[:B] if pad else dig


def pallas_available() -> bool:
    """Availability of the production (compiled, non-interpret) kernel:
    the shared accelerator probe gated by PLENUM_TPU_SHA256_BACKEND
    (ops/mesh.pallas_backend_enabled — one decision per process,
    cleared with the platform probe)."""
    from plenum_tpu.ops import mesh as mesh_mod
    return mesh_mod.pallas_backend_enabled(PALLAS_ENV)


def sha256_many_pallas(msgs, interpret: bool = False) -> list:
    """Batched SHA-256 over bytes through the Pallas kernel — the
    byte-level test/bench entry (production routes through
    ops/sha256.sha256_blocks_routed)."""
    from plenum_tpu.ops.sha256 import digests_to_bytes, pad_messages
    if not msgs:
        return []
    words, nvalid, nblocks = pad_messages(msgs)
    dig = sha256_blocks(jnp.asarray(words), jnp.asarray(nvalid),
                        nblocks, interpret)
    return digests_to_bytes(np.asarray(dig))
