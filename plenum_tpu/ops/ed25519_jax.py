"""Batched ed25519 signature verification on TPU (JAX).

The reference verifies client-request and propagate signatures one at a
time through libsodium (`plenum/server/client_authn.py:84`,
`stp_core/crypto/nacl_wrappers.py`). This kernel verifies THOUSANDS of
signatures per device dispatch — the north-star batch path of
BASELINE.json ("ed25519 batch verify 1/1k/100k").

TPU-first design:
 - Field arithmetic over GF(2^255-19) in radix 2^13: 20 int32 limbs per
   element. Limb products are ≤ 2^26 and column sums ≤ 20·2^26 < 2^31, so
   everything fits native int32 on the VPU — no 64-bit emulation, no
   floats, fully deterministic.
 - All control flow is static: `lax.fori_loop` over 256 scalar bits with
   per-bit conditional point additions via `jnp.where` (constant shape —
   XLA-friendly, and constant-time as a bonus).
 - Host does the cheap data-dependent work (SHA-512 of R||A||M via
   hashlib's C core, canonicality checks, limb packing); the device does
   the ~500 field multiplications per signature that dominate.
 - Verification is cofactorless: [S]B == R + [k]A, computed as
   [S]B + [k](-A) vs decompressed R, batched over the whole array.

Layout: an element is [..., 20] int32; batch ops are elementwise over the
leading axes, so the batch axis shards across a device mesh with zero
collectives (embarrassingly parallel). `verify_batch_async` routes
batches through the production mesh dispatcher (`ops/mesh.DeviceMesh`):
on a multi-chip host, batches at or above `Config.MESH_SHARD_MIN` are
bucket-padded per device and launched as ONE SPMD program over every
chip; single-device hosts and small batches take the unchanged
passthrough path.
"""
from __future__ import annotations

import functools
import hashlib
from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from plenum_tpu.observability import telemetry as _tmy

# ---------------------------------------------------------------- constants

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1

P = 2 ** 255 - 19
L = 2 ** 252 + 27742317777372353535851937790883648493
D_INT = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)
G_Y_INT = (4 * pow(5, P - 2, P)) % P


def _int_to_limbs(v: int) -> np.ndarray:
    out = np.zeros(NLIMB, dtype=np.int32)
    for i in range(NLIMB):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def _limbs_to_int(limbs) -> int:
    v = 0
    for i in reversed(range(len(limbs))):
        v = (v << RADIX) | int(limbs[i])
    return v


def _exp_bits(e: int) -> np.ndarray:
    """Exponent bits, msb first."""
    return np.array([int(b) for b in bin(e)[2:]], dtype=np.int32)


_D_L = _int_to_limbs(D_INT)
_TWOD_L = _int_to_limbs(2 * D_INT % P)
_SQRT_M1_L = _int_to_limbs(SQRT_M1_INT)
_ONE_L = _int_to_limbs(1)
_E58_BITS = _exp_bits((P - 5) // 8)

# 8p in radix-2^13 digits, spread so that every limb of the constant
# dominates any normalized operand limb (enables borrow-free subtraction:
# a - b computed as a + SPREAD_8P - b with nonnegative limbs throughout).
def _spread_8p() -> np.ndarray:
    d = _int_to_limbs(8 * P).astype(np.int64)
    e = d.copy()
    e[0] += 1 << (RADIX + 1)
    for i in range(1, NLIMB - 1):
        e[i] += (1 << (RADIX + 1)) - 2
    e[NLIMB - 1] -= 2
    assert _limbs_to_int(e) == 8 * P
    assert all(e[i] >= MASK + 2 for i in range(NLIMB - 1))
    assert e[NLIMB - 1] >= 1 << 10  # dominates the ≤2^9 top limb invariant
    return e.astype(np.int32)


_SPREAD_8P = _spread_8p()


# ----------------------------------------------------- field arithmetic

# Anti-diagonal scatter matrix: flat outer-product index (i*20+j) → column
# i+j. One [.., 400]×[400, 42] int32 matmul replaces 400 unrolled
# multiply-adds — tiny XLA graphs and VPU-friendly vector work.
def _fold_matrix() -> np.ndarray:
    m = np.zeros((NLIMB * NLIMB, 2 * NLIMB + 2), dtype=np.int32)
    for i in range(NLIMB):
        for j in range(NLIMB):
            m[i * NLIMB + j, i + j] = 1
    return m


_FOLD_MAT = _fold_matrix()


def _shift_up(c):
    """Shift columns up one position (carry from col k lands in col k+1)."""
    pad = [(0, 0)] * (c.ndim - 1) + [(1, 0)]
    return jnp.pad(c[..., :-1], pad)


def _carry_round(c):
    """One parallel carry step over all columns; top carry must be vacuous
    (caller guarantees headroom in the last column)."""
    cr = c >> RADIX
    return (c & MASK) + _shift_up(cr)


def _carry_wrap_round(c):
    """Parallel carry on 20 columns where the top carry wraps to column 0
    multiplied by 608 (2^260 ≡ 19·2^5 mod p)."""
    cr = c >> RADIX
    wrapped = jnp.concatenate([cr[..., -1:] * 608, cr[..., :-1]], axis=-1)
    return (c & MASK) + wrapped


def _finalize20(c):
    """Normalize 20 columns (each < 2^25 — the verified headroom: the
    first wrap round's cr·608 term then stays < 2^21, far from int32
    overflow) to the invariant: limbs ≤ MASK+1, top limb < 2^9 (bits
    ≥ 255 folded back ×19)."""
    c = _carry_wrap_round(c)
    c = _carry_wrap_round(c)
    top = c[..., -1:] >> 8
    c = jnp.concatenate([c[..., :1] + top * 19, c[..., 1:-1],
                         c[..., -1:] - (top << 8)], axis=-1)
    return _carry_wrap_round(c)


def fmul(a, b):
    """Field multiply. a, b: [..., 20] int32, limbs ≤ MASK+1, top < 2^9."""
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(outer.shape[:-2] + (NLIMB * NLIMB,))
    c = flat @ jnp.asarray(_FOLD_MAT)          # [..., 42], cols < 20·2^26
    c = _carry_round(c)
    c = _carry_round(c)
    c = _carry_round(c)                         # all 42 cols ≤ MASK+1
    # fold: col 20+k carries weight 2^260·2^13k ≡ 608·2^13k, col 40+k
    # carries (2^260)²·2^13k ≡ 608²·2^13k (cols 40-41 hold only carry
    # residue ≤ 2^5 after the rounds above, so 608² ≈ 2^18.5 is safe)
    extra = c[..., 40:42] * (608 * 608)
    pad = [(0, 0)] * (extra.ndim - 1) + [(0, NLIMB - 2)]
    c = c[..., :20] + c[..., 20:40] * 608 + jnp.pad(extra, pad)
    return _finalize20(c)                       # input cols < 2^25


def fsq(a):
    return fmul(a, a)


def fadd(a, b):
    return _finalize20(a + b)


def fsub(a, b):
    return _finalize20(a + jnp.asarray(_SPREAD_8P) - b)


def fneg(a):
    return fsub(jnp.zeros_like(a), a)


def _stack(c: List):
    return jnp.stack(c, axis=-1)


def _cols(x):
    return [x[..., i] for i in range(x.shape[-1])]


def fcanon(x):
    """Canonical representative in [0, p): conditional single subtract of p.

    Input invariant (post-reduction limbs) bounds the value below 2p.
    """
    c = _cols(x)
    # t = x + 19, full carry: bit 255 of t tells whether x >= p
    t = [ci for ci in c]
    t[0] = t[0] + 19
    for k in range(NLIMB - 1):
        cr = t[k] >> RADIX
        t[k] = t[k] - (cr << RADIX)
        t[k + 1] = t[k + 1] + cr
    q = t[NLIMB - 1] >> 8  # 0 or 1
    # x - q*p  ==  x + q*19 - q*2^255
    r = [ci for ci in c]
    r[0] = r[0] + q * 19
    r[NLIMB - 1] = r[NLIMB - 1] - (q << 8)
    for k in range(NLIMB - 1):
        cr = r[k] >> RADIX  # arithmetic shift: signed carries OK
        r[k] = r[k] - (cr << RADIX)
        r[k + 1] = r[k + 1] + cr
    return _stack(r)


def fiszero(x):
    """x (post-reduction) ≡ 0 mod p?  → bool[...]."""
    xc = fcanon(x)
    return jnp.all(xc == 0, axis=-1)


def feq(a, b):
    return fiszero(fsub(a, b))


def fpow(x, bits: np.ndarray):
    """x^e for fixed public exponent given as msb-first bit array."""
    bits_j = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), x.shape)

    def body(i, acc):
        acc = fsq(acc)
        withmul = fmul(acc, x)
        return jnp.where((bits_j[i] == 1), withmul, acc)

    return lax.fori_loop(0, len(bits), body, one)


def _sqn(x, n: int):
    def body(i, acc):
        return fsq(acc)
    return lax.fori_loop(0, n, body, x) if n > 4 else \
        functools.reduce(lambda a, _: fsq(a), range(n), x)


def pow_p58(x):
    """x^((p-5)/8) via the standard ed25519 addition chain (ref10
    pow22523 structure): 252 squarings + 11 multiplies instead of
    square-and-multiply's ~125 extra multiplies — decompress is on the
    critical path of every verify."""
    z2 = fsq(x)                       # 2
    z9 = fmul(_sqn(z2, 2), x)         # 9 = 2^3+1
    z11 = fmul(z9, z2)                # 11
    z22 = fsq(z11)                    # 22
    z_5_0 = fmul(z22, z9)             # 2^5 - 2^0
    z_10_0 = fmul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = fmul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = fmul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = fmul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = fmul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = fmul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = fmul(_sqn(z_200_0, 50), z_50_0)
    return fmul(_sqn(z_250_0, 2), x)  # 2^252 - 3


# ----------------------------------------------------- point arithmetic
# Extended twisted-Edwards coordinates (X, Y, Z, T), a = -1.

def pt_double(X, Y, Z, T):
    A = fsq(X)
    B = fsq(Y)
    C = fadd(fsq(Z), fsq(Z))
    E = fsub(fsub(fsq(fadd(X, Y)), A), B)
    G = fsub(B, A)
    F = fsub(G, C)
    H = fsub(fneg(A), B)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


def pt_add(X1, Y1, Z1, T1, X2, Y2, Z2, T2):
    A = fmul(fsub(Y1, X1), fsub(Y2, X2))
    B = fmul(fadd(Y1, X1), fadd(Y2, X2))
    C = fmul(fmul(T1, jnp.broadcast_to(jnp.asarray(_TWOD_L), T1.shape)), T2)
    Dv = fadd(fmul(Z1, Z2), fmul(Z1, Z2))
    E = fsub(B, A)
    F = fsub(Dv, C)
    G = fadd(Dv, C)
    H = fadd(B, A)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


def _pt_add_prescaled(X1, Y1, Z1, T1, X2, Y2, Z2, T2_2d):
    """pt_add where the second point's T is pre-multiplied by 2d
    (runtime window tables): 8 field muls."""
    A = fmul(fsub(Y1, X1), fsub(Y2, X2))
    B = fmul(fadd(Y1, X1), fadd(Y2, X2))
    C = fmul(T1, T2_2d)
    Dv = fmul(fadd(Z1, Z1), Z2)
    E = fsub(B, A)
    F = fsub(Dv, C)
    G = fadd(Dv, C)
    H = fadd(B, A)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


def _select_pt(cond, pa, pb):
    c = cond[..., None]
    return tuple(jnp.where(c, a, b) for a, b in zip(pa, pb))


def decompress(ylimbs, sign):
    """(x, ok): recover x from y and sign bit; ok=False if not on curve."""
    yy = fsq(ylimbs)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), ylimbs.shape)
    u = fsub(yy, one)
    v = fadd(fmul(jnp.broadcast_to(jnp.asarray(_D_L), yy.shape), yy), one)
    v2 = fsq(v)
    v3 = fmul(v2, v)
    v7 = fmul(fsq(v3), v)
    x = fmul(fmul(u, v3), pow_p58(fmul(u, v7)))
    vxx = fmul(v, fsq(x))
    is_root = feq(vxx, u)
    is_neg_root = fiszero(fadd(vxx, u))
    x = jnp.where((is_neg_root & ~is_root)[..., None],
                  fmul(x, jnp.broadcast_to(jnp.asarray(_SQRT_M1_L), x.shape)),
                  x)
    ok = is_root | is_neg_root
    xc = fcanon(x)
    x_zero = jnp.all(xc == 0, axis=-1)
    ok = ok & ~(x_zero & (sign == 1))
    parity = xc[..., 0] & 1
    x = jnp.where((parity != sign)[..., None], fneg(xc), xc)
    return x, ok


def pt_add_niels(X1, Y1, Z1, T1, n_sub, n_add, n_t2d):
    """Mixed addition with a precomputed (Y2-X2, Y2+X2, 2d*T2, Z2=1)
    "niels" point: 7 field muls instead of pt_add's 9 (the 2d mult and
    the Z2 mult are folded into the table entry). Complete formulas —
    the identity entry (1, 1, 0) is handled with no special case."""
    A = fmul(fsub(Y1, X1), n_sub)
    B = fmul(fadd(Y1, X1), n_add)
    C = fmul(T1, n_t2d)
    Dv = fadd(Z1, Z1)
    E = fsub(B, A)
    F = fsub(Dv, C)
    G = fadd(Dv, C)
    H = fadd(B, A)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


# --------------------------------------- host-side integer curve ops
# (table construction at import time; python ints, exact)

def _ed_add_affine(p1, p2):
    """Affine Edwards addition over python ints (import-time tables)."""
    x1, y1 = p1
    x2, y2 = p2
    dxy = D_INT * x1 % P * x2 % P * y1 % P * y2 % P
    x3 = (x1 * y2 + x2 * y1) % P * pow(1 + dxy, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) % P * pow(1 - dxy, P - 2, P) % P
    return x3, y3


def _base_affine():
    gy = G_Y_INT
    u = (gy * gy - 1) % P
    v = (D_INT * gy * gy + 1) % P
    gx = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * gx * gx - u) % P != 0:
        gx = gx * SQRT_M1_INT % P
    if gx & 1 != 0:
        gx = P - gx
    return gx, gy


def _niels_from_affine(pt) -> List[np.ndarray]:
    x, y = pt
    return [_int_to_limbs((y - x) % P), _int_to_limbs((y + x) % P),
            _int_to_limbs(2 * D_INT * x % P * y % P)]


def _build_base_window_table() -> List[np.ndarray]:
    """d*B for d=0..15 in niels form → 3 constant arrays [16, 20]."""
    entries = [[_int_to_limbs(1), _int_to_limbs(1), _int_to_limbs(0)]]
    acc = None
    base = _base_affine()
    for d in range(1, 16):
        acc = base if acc is None else _ed_add_affine(acc, base)
        entries.append(_niels_from_affine(acc))
    return [np.stack([e[c] for e in entries]) for c in range(3)]


_NB_SUB, _NB_ADD, _NB_T2D = _build_base_window_table()


# ----------------------------------------------------- the verify kernel

def _base_point_ext() -> List[np.ndarray]:
    gy = G_Y_INT
    u = (gy * gy - 1) % P
    v = (D_INT * gy * gy + 1) % P
    gx = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * gx * gx - u) % P != 0:
        gx = gx * SQRT_M1_INT % P
    if gx & 1 != 0:
        gx = P - gx
    return [_int_to_limbs(gx), _int_to_limbs(gy), _int_to_limbs(1),
            _int_to_limbs(gx * gy % P)]


_B_EXT = _base_point_ext()


def _digits4(words):
    """[B, 8] uint32 → [B, 64] int32 4-bit digits, least significant
    digit first."""
    shifts = jnp.arange(0, 32, 4, dtype=jnp.uint32)        # [8]
    d = (words[..., :, None] >> shifts[None, None, :]) & 0xF  # [B, 8, 8]
    return d.reshape(d.shape[:-2] + (64,)).astype(jnp.int32)


def _select_const_niels(onehot):
    """One-hot [B,16] → niels point from the constant base table."""
    return (onehot @ jnp.asarray(_NB_SUB),
            onehot @ jnp.asarray(_NB_ADD),
            onehot @ jnp.asarray(_NB_T2D))


def _select_batched(onehot, table):
    """One-hot [B,16] × per-batch table [B,16,20] → [B,20] per coord."""
    return tuple(jnp.einsum("bd,bdl->bl", onehot, t) for t in table)


@jax.jit
def _verify_kernel(ay, asign, ry, rsign, s_words, k_words):
    """All inputs batched; returns bool[B].

    ay/ry: [B, 20] int32 limbs of the y coordinates (canonical, < p)
    asign/rsign: [B] int32 sign bits
    s_words/k_words: [B, 8] uint32 little-endian scalar words

    Interleaved 4-bit windowed double-scalar multiplication
    (VERDICT round-1 item 5): per 64 windows, 4 shared doublings + one
    niels-form add from the CONSTANT d*B table (fixed-base, 7 muls) +
    one add from the per-signature d*(-A) table (8 muls, 2d*T
    pre-scaled) — ~2.4x fewer field muls than bitwise double-and-add
    with two conditional adds per bit. Digit selection is one-hot
    matmuls (constant-shape, MXU/VPU-friendly, no gathers).
    """
    ax, ok_a = decompress(ay, asign)
    rx, ok_r = decompress(ry, rsign)

    one = jnp.broadcast_to(jnp.asarray(_ONE_L), ay.shape)
    zero = jnp.zeros_like(ay)
    twod = jnp.broadcast_to(jnp.asarray(_TWOD_L), ay.shape)

    # ---- per-signature table: d * (-A), d = 0..15, extended coords
    # with T pre-scaled by 2d (so the loop add costs 8 muls)
    nax = fneg(ax)
    na = (nax, ay, one, fmul(nax, ay))
    tab = [(zero, one, one, zero), na]
    for d in range(2, 16):
        if d % 2 == 0:
            tab.append(pt_double(*tab[d // 2]))
        else:
            tab.append(pt_add(*tab[d - 1], *na))
    tab_x = jnp.stack([t[0] for t in tab], axis=-2)   # [B, 16, 20]
    tab_y = jnp.stack([t[1] for t in tab], axis=-2)
    tab_z = jnp.stack([t[2] for t in tab], axis=-2)
    tab_t2d = jnp.stack([fmul(t[3], twod) for t in tab], axis=-2)
    a_table = (tab_x, tab_y, tab_z, tab_t2d)

    sd = _digits4(s_words)   # [B, 64]
    kd = _digits4(k_words)

    ident = (zero, one, one, zero)
    eye16 = jnp.eye(16, dtype=jnp.int32)

    def body(i, st):
        w = 63 - i
        st = pt_double(*pt_double(*pt_double(*pt_double(*st))))
        s_dig = lax.dynamic_index_in_dim(sd, w, axis=-1, keepdims=False)
        k_dig = lax.dynamic_index_in_dim(kd, w, axis=-1, keepdims=False)
        s_oh = eye16[s_dig]                     # [B, 16]
        k_oh = eye16[k_dig]
        st = pt_add_niels(*st, *_select_const_niels(s_oh))
        x2, y2, z2, t2d2 = _select_batched(k_oh, a_table)
        st = _pt_add_prescaled(*st, x2, y2, z2, t2d2)
        return st

    X, Y, Z, _ = lax.fori_loop(0, 64, body, ident)

    ok_x = fiszero(fsub(fmul(rx, Z), X))
    ok_y = fiszero(fsub(fmul(ry, Z), Y))
    return ok_a & ok_r & ok_x & ok_y


# ----------------------------------------------------- host-side wrapper

def _pack_fe(values: Sequence[int]) -> np.ndarray:
    out = np.empty((len(values), NLIMB), dtype=np.int32)
    for i, v in enumerate(values):
        for k in range(NLIMB):
            out[i, k] = v & MASK
            v >>= RADIX
    return out


def _pack_words(values: Sequence[int]) -> np.ndarray:
    out = np.empty((len(values), 8), dtype=np.uint32)
    for i, v in enumerate(values):
        for k in range(8):
            out[i, k] = v & 0xFFFFFFFF
            v >>= 32
    return out


def _bit_fold_matrix() -> np.ndarray:
    """[256, 20] f32: bit j of a little-endian 256-bit value contributes
    2^(j-13i) to limb i (radix-2^13). Values stay < 2^13 — exact in f32,
    so limb packing is one numpy matmul instead of a per-item loop."""
    m = np.zeros((256, NLIMB), dtype=np.float32)
    for j in range(256):
        i = j // RADIX
        if i < NLIMB:
            m[j, i] = float(1 << (j - RADIX * i))
    return m


_BIT_FOLD = _bit_fold_matrix()


def _le_words(a_bytes: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 → [B, 4] uint64 little-endian words."""
    return a_bytes.view(np.uint64).reshape(a_bytes.shape[0], 4)


def _ge_const(words: np.ndarray, const: int) -> np.ndarray:
    """Vectorized (value >= const) over [B, 4] LE uint64 words."""
    cw = np.array([(const >> (64 * i)) & 0xFFFFFFFFFFFFFFFF
                   for i in range(4)], dtype=np.uint64)
    ge = np.zeros(words.shape[0], dtype=bool)
    decided = np.zeros(words.shape[0], dtype=bool)
    for i in (3, 2, 1, 0):  # most significant first
        gt = words[:, i] > cw[i]
        lt = words[:, i] < cw[i]
        ge |= gt & ~decided
        decided |= gt | lt
    ge |= ~decided  # equal ⇒ >=
    return ge


def _limbs_from_bytes(a_bytes: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 (LE) → [B, 20] int32 radix-2^13 limbs, vectorized."""
    bits = np.unpackbits(a_bytes, axis=1, bitorder="little")  # [B, 256]
    return (bits.astype(np.float32) @ _BIT_FOLD).astype(np.int32)


def host_pack(msgs: Sequence[bytes], sigs: Sequence[bytes],
              verkeys: Sequence[bytes]):
    """Host-side preprocessing: parse/canonicality-check sigs and keys,
    compute k = SHA-512(R||A||M) mod L (hashlib C core), pack limb arrays.

    → ([ay, asign, ry, rsign, s_words, k_words] host np arrays — the
    jit transfers them once; keeping them in numpy lets callers pad the
    batch axis without device round-trips — and valid bool[B])

    Fully vectorized (VERDICT round-1: the device kernel is ~1ms for 8k
    sigs — a per-item python loop here would dominate the whole verify):
    numpy views/unpackbits/matmul do the parsing; the only per-item C
    calls are SHA-512 and the 512→253-bit modular reduction of k.
    """
    n = len(msgs)
    assert len(sigs) == n and len(verkeys) == n
    valid = np.ones(n, dtype=bool)

    DUMMY_SIG = b"\x00" * 64
    DUMMY_VK = b"\x01" + b"\x00" * 31
    norm_sigs = []
    norm_vks = []
    for i in range(n):
        if len(sigs[i]) != 64 or len(verkeys[i]) != 32:
            valid[i] = False
            norm_sigs.append(DUMMY_SIG)
            norm_vks.append(DUMMY_VK)
        else:
            norm_sigs.append(bytes(sigs[i]))
            norm_vks.append(bytes(verkeys[i]))

    sig_b = np.frombuffer(b"".join(norm_sigs), dtype=np.uint8).reshape(n, 64)
    vk_b = np.frombuffer(b"".join(norm_vks), dtype=np.uint8).reshape(n, 32)
    r_b = np.ascontiguousarray(sig_b[:, :32])
    s_b = np.ascontiguousarray(sig_b[:, 32:])

    asign = (vk_b[:, 31] >> 7).astype(np.int32)
    rsign = (r_b[:, 31] >> 7).astype(np.int32)
    ay_b = vk_b.copy()
    ay_b[:, 31] &= 0x7F
    ry_b = r_b.copy()
    ry_b[:, 31] &= 0x7F

    # canonicality: y < p, s < L (vectorized big-int compares)
    bad = _ge_const(_le_words(ay_b), P) | _ge_const(_le_words(ry_b), P) \
        | _ge_const(_le_words(s_b), L)
    valid &= ~bad
    if bad.any():
        idx = np.nonzero(bad)[0]
        ay_b[idx] = 0
        ry_b[idx] = 0
        ay_b[idx, 0] = 1
        ry_b[idx, 0] = 1
        s_b = s_b.copy()
        s_b[idx] = 0

    # k = SHA-512(R || A || M) mod L — hashlib + bigint mod are the only
    # per-item C calls left
    k_parts = []
    for i in range(n):
        h = hashlib.sha512()
        h.update(norm_sigs[i][:32])
        h.update(norm_vks[i])
        h.update(msgs[i])
        k_int = int.from_bytes(h.digest(), "little") % L
        k_parts.append(k_int.to_bytes(32, "little"))
    k_b = np.frombuffer(b"".join(k_parts), dtype=np.uint8).reshape(n, 32)

    arrays = [_limbs_from_bytes(ay_b),
              asign,
              _limbs_from_bytes(ry_b),
              rsign,
              np.ascontiguousarray(s_b).view(np.uint32).reshape(n, 8),
              k_b.view(np.uint32).reshape(n, 8)]
    return arrays, valid


def verify_batch(msgs: Sequence[bytes], sigs: Sequence[bytes],
                 verkeys: Sequence[bytes]) -> np.ndarray:
    """Batched cofactorless ed25519 verify → np.bool_ array [B].

    Host does the cheap data-dependent prep (host_pack); the device does
    all elliptic-curve math in one dispatch.
    """
    ok_dev, valid, n = verify_batch_async(msgs, sigs, verkeys)
    if n == 0:
        return np.zeros(0, dtype=bool)
    return np.asarray(ok_dev)[:n] & valid


def launch_lanes(n: int) -> int:
    """The padded batch-lane count a verify_batch_async(n) launch will
    occupy: the mesh bucket when the batch shards, the power-of-two
    (min 8) single-device bucket otherwise. Single-sourced so callers
    that account lane occupancy for their OWN seam (the coalescing hub)
    report the same bucket the launch actually pays for."""
    if n <= 0:
        return 0
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    if m.should_shard(n):
        return m.padded_size(n)
    padded = 8
    while padded < n:
        padded *= 2
    return padded


def verify_batch_async(msgs: Sequence[bytes], sigs: Sequence[bytes],
                       verkeys: Sequence[bytes]):
    """Non-blocking batched verify: enqueues the device computation and
    returns (ok_device_array, valid_host_bools, n) immediately — JAX
    dispatch is async, so the caller overlaps host work with the device
    round trip and materializes later (np.asarray(ok)[:n] & valid).

    Multi-chip: batches clearing the mesh gate (ops/mesh.py) are
    bucket-padded per device and launched as one batch-axis-sharded
    SPMD program over every chip (zero collectives); otherwise the
    single-device path below is unchanged."""
    n = len(msgs)
    if n == 0:
        return None, np.zeros(0, dtype=bool), 0
    arrays, valid = host_pack(msgs, sigs, verkeys)
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    padded = launch_lanes(n)
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_ED25519, n, padded, shape=padded)
    if m.should_shard(n):
        # the mesh path runs the XLA kernel: it SPMD-partitions over the
        # batch axis with no code change, whereas the Pallas kernel is a
        # per-chip program (its per-device halves still run the winning
        # tile grid when each shard fills a block)
        arrays = mesh_mod.pad_rows(arrays, padded)
        ok = m.dispatch(_verify_kernel, arrays, n=n)
        return ok, valid, n
    m.note_passthrough(n)
    # pad the batch axis to the next power of two (min 8) by repeating
    # row 0 so every size in [1, 2^k] shares one compiled kernel —
    # variable pool queue depths must not trigger XLA recompiles
    if padded != n:
        arrays = [np.concatenate(
            [a, np.repeat(a[:1], padded - n, axis=0)], axis=0)
            for a in arrays]
    ok = _dispatch_kernel(*arrays)
    return ok, valid, n


# Backend selection: the Pallas whole-verify kernel (its VMEM-resident
# limb registers avoid the per-fmul HBM round trips) for any batch
# filling a block on a TPU — ~2x the XLA expression at every block
# count, see _dispatch_kernel; the XLA kernel otherwise (smaller
# batches, CPU tests, or any Pallas failure → permanent fallback).
_ED25519_PALLAS_ENV = "PLENUM_TPU_ED25519_BACKEND"


def _pallas_available() -> bool:
    # ONE shared probe-backed availability gate for every Pallas
    # kernel family (ops/mesh.pallas_backend_enabled) — probing
    # jax.devices()[0] here would force backend init and assume
    # device 0, and a private cache would escape dryrun_multichip's
    # probe reset
    from plenum_tpu.ops import mesh as mesh_mod
    return mesh_mod.pallas_backend_enabled(_ED25519_PALLAS_ENV)


_PALLAS_VALIDATED = set()      # grid sizes whose execution has completed


def _dispatch_kernel(ay, asign, ry, rsign, s_words, k_words):
    from plenum_tpu.ops import ed25519_pallas as edp
    # at R=32 blocks the pallas kernel wins from ONE block up (4096:
    # 99ms vs 190ms XLA; 16384: 236ms vs 518ms); below a block the XLA
    # kernel serves (small batches don't fill the tile grid)
    while _pallas_available() and ay.shape[0] >= edp.BLOCK:
        n_blocks = -(-ay.shape[0] // edp.BLOCK)
        try:
            ok = edp.verify_kernel(ay, asign, ry, rsign,
                                   s_words, k_words)
            if n_blocks not in _PALLAS_VALIDATED:
                # JAX dispatch is async: runtime failures (VMEM/OOM at
                # an untested grid size) would otherwise surface at the
                # caller's np.asarray, outside this except, and the
                # fallback would never engage. Block ONCE per grid size
                # to prove execution; later calls stay fully async.
                # deliberate ONE-TIME sync per grid size to prove
                # execution; later calls with this grid stay fully async
                ok.block_until_ready()  # plenum-lint: disable=PT002
                _PALLAS_VALIDATED.add(n_blocks)
            return ok
        except Exception:  # pragma: no cover  # plenum-lint: disable=PT006
            # the fallback engine itself: ANY Pallas failure (VMEM,
            # lowering, runtime) must step down to the XLA kernel,
            # never crash a verify
            logger = __import__("logging").getLogger(__name__)
            if edp.BLOCK_R > 16:
                # R=32 needs ~26MB VMEM: a smaller-VMEM TPU generation
                # should step down to the R=16 kernel (fits the 16MB
                # default) before giving up on Pallas entirely
                edp.BLOCK_R //= 2
                edp.BLOCK = edp.BLOCK_R * edp.BLOCK_L
                edp._build_verify.cache_clear()
                _PALLAS_VALIDATED.clear()
                logger.exception(
                    "pallas verify failed; retrying with BLOCK_R=%d",
                    edp.BLOCK_R)
                continue
            logger.exception("pallas verify failed; falling back to XLA")
            from plenum_tpu.ops import mesh as mesh_mod
            mesh_mod.disable_pallas_backend(_ED25519_PALLAS_ENV)
    return _verify_kernel(ay, asign, ry, rsign, s_words, k_words)
