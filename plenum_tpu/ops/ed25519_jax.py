"""Batched ed25519 signature verification on TPU (JAX).

The reference verifies client-request and propagate signatures one at a
time through libsodium (`plenum/server/client_authn.py:84`,
`stp_core/crypto/nacl_wrappers.py`). This kernel verifies THOUSANDS of
signatures per device dispatch — the north-star batch path of
BASELINE.json ("ed25519 batch verify 1/1k/100k").

TPU-first design:
 - Field arithmetic over GF(2^255-19) in radix 2^13: 20 int32 limbs per
   element. Limb products are ≤ 2^26 and column sums ≤ 20·2^26 < 2^31, so
   everything fits native int32 on the VPU — no 64-bit emulation, no
   floats, fully deterministic.
 - All control flow is static: `lax.fori_loop` over 256 scalar bits with
   per-bit conditional point additions via `jnp.where` (constant shape —
   XLA-friendly, and constant-time as a bonus).
 - Host does the cheap data-dependent work (SHA-512 of R||A||M via
   hashlib's C core, canonicality checks, limb packing); the device does
   the ~500 field multiplications per signature that dominate.
 - Verification is cofactorless: [S]B == R + [k]A, computed as
   [S]B + [k](-A) vs decompressed R, batched over the whole array.

Layout: an element is [..., 20] int32; batch ops are elementwise over the
leading axes, so `jax.sharding` over the batch axis scales this across a
device mesh with zero collectives (embarrassingly parallel).
"""
from __future__ import annotations

import functools
import hashlib
from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------- constants

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1

P = 2 ** 255 - 19
L = 2 ** 252 + 27742317777372353535851937790883648493
D_INT = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)
G_Y_INT = (4 * pow(5, P - 2, P)) % P


def _int_to_limbs(v: int) -> np.ndarray:
    out = np.zeros(NLIMB, dtype=np.int32)
    for i in range(NLIMB):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def _limbs_to_int(limbs) -> int:
    v = 0
    for i in reversed(range(len(limbs))):
        v = (v << RADIX) | int(limbs[i])
    return v


def _exp_bits(e: int) -> np.ndarray:
    """Exponent bits, msb first."""
    return np.array([int(b) for b in bin(e)[2:]], dtype=np.int32)


_D_L = _int_to_limbs(D_INT)
_TWOD_L = _int_to_limbs(2 * D_INT % P)
_SQRT_M1_L = _int_to_limbs(SQRT_M1_INT)
_ONE_L = _int_to_limbs(1)
_E58_BITS = _exp_bits((P - 5) // 8)

# 8p in radix-2^13 digits, spread so that every limb of the constant
# dominates any normalized operand limb (enables borrow-free subtraction:
# a - b computed as a + SPREAD_8P - b with nonnegative limbs throughout).
def _spread_8p() -> np.ndarray:
    d = _int_to_limbs(8 * P).astype(np.int64)
    e = d.copy()
    e[0] += 1 << (RADIX + 1)
    for i in range(1, NLIMB - 1):
        e[i] += (1 << (RADIX + 1)) - 2
    e[NLIMB - 1] -= 2
    assert _limbs_to_int(e) == 8 * P
    assert all(e[i] >= MASK + 2 for i in range(NLIMB - 1))
    assert e[NLIMB - 1] >= 1 << 10  # dominates the ≤2^9 top limb invariant
    return e.astype(np.int32)


_SPREAD_8P = _spread_8p()


# ----------------------------------------------------- field arithmetic

# Anti-diagonal scatter matrix: flat outer-product index (i*20+j) → column
# i+j. One [.., 400]×[400, 42] int32 matmul replaces 400 unrolled
# multiply-adds — tiny XLA graphs and VPU-friendly vector work.
def _fold_matrix() -> np.ndarray:
    m = np.zeros((NLIMB * NLIMB, 2 * NLIMB + 2), dtype=np.int32)
    for i in range(NLIMB):
        for j in range(NLIMB):
            m[i * NLIMB + j, i + j] = 1
    return m


_FOLD_MAT = _fold_matrix()


def _shift_up(c):
    """Shift columns up one position (carry from col k lands in col k+1)."""
    pad = [(0, 0)] * (c.ndim - 1) + [(1, 0)]
    return jnp.pad(c[..., :-1], pad)


def _carry_round(c):
    """One parallel carry step over all columns; top carry must be vacuous
    (caller guarantees headroom in the last column)."""
    cr = c >> RADIX
    return (c & MASK) + _shift_up(cr)


def _carry_wrap_round(c):
    """Parallel carry on 20 columns where the top carry wraps to column 0
    multiplied by 608 (2^260 ≡ 19·2^5 mod p)."""
    cr = c >> RADIX
    wrapped = jnp.concatenate([cr[..., -1:] * 608, cr[..., :-1]], axis=-1)
    return (c & MASK) + wrapped


def _finalize20(c):
    """Normalize 20 columns (each < 2^25 — the verified headroom: the
    first wrap round's cr·608 term then stays < 2^21, far from int32
    overflow) to the invariant: limbs ≤ MASK+1, top limb < 2^9 (bits
    ≥ 255 folded back ×19)."""
    c = _carry_wrap_round(c)
    c = _carry_wrap_round(c)
    top = c[..., -1:] >> 8
    c = jnp.concatenate([c[..., :1] + top * 19, c[..., 1:-1],
                         c[..., -1:] - (top << 8)], axis=-1)
    return _carry_wrap_round(c)


def fmul(a, b):
    """Field multiply. a, b: [..., 20] int32, limbs ≤ MASK+1, top < 2^9."""
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(outer.shape[:-2] + (NLIMB * NLIMB,))
    c = flat @ jnp.asarray(_FOLD_MAT)          # [..., 42], cols < 20·2^26
    c = _carry_round(c)
    c = _carry_round(c)
    c = _carry_round(c)                         # all 42 cols ≤ MASK+1
    # fold: col 20+k carries weight 2^260·2^13k ≡ 608·2^13k, col 40+k
    # carries (2^260)²·2^13k ≡ 608²·2^13k (cols 40-41 hold only carry
    # residue ≤ 2^5 after the rounds above, so 608² ≈ 2^18.5 is safe)
    extra = c[..., 40:42] * (608 * 608)
    pad = [(0, 0)] * (extra.ndim - 1) + [(0, NLIMB - 2)]
    c = c[..., :20] + c[..., 20:40] * 608 + jnp.pad(extra, pad)
    return _finalize20(c)                       # input cols < 2^25


def fsq(a):
    return fmul(a, a)


def fadd(a, b):
    return _finalize20(a + b)


def fsub(a, b):
    return _finalize20(a + jnp.asarray(_SPREAD_8P) - b)


def fneg(a):
    return fsub(jnp.zeros_like(a), a)


def _stack(c: List):
    return jnp.stack(c, axis=-1)


def _cols(x):
    return [x[..., i] for i in range(x.shape[-1])]


def fcanon(x):
    """Canonical representative in [0, p): conditional single subtract of p.

    Input invariant (post-reduction limbs) bounds the value below 2p.
    """
    c = _cols(x)
    # t = x + 19, full carry: bit 255 of t tells whether x >= p
    t = [ci for ci in c]
    t[0] = t[0] + 19
    for k in range(NLIMB - 1):
        cr = t[k] >> RADIX
        t[k] = t[k] - (cr << RADIX)
        t[k + 1] = t[k + 1] + cr
    q = t[NLIMB - 1] >> 8  # 0 or 1
    # x - q*p  ==  x + q*19 - q*2^255
    r = [ci for ci in c]
    r[0] = r[0] + q * 19
    r[NLIMB - 1] = r[NLIMB - 1] - (q << 8)
    for k in range(NLIMB - 1):
        cr = r[k] >> RADIX  # arithmetic shift: signed carries OK
        r[k] = r[k] - (cr << RADIX)
        r[k + 1] = r[k + 1] + cr
    return _stack(r)


def fiszero(x):
    """x (post-reduction) ≡ 0 mod p?  → bool[...]."""
    xc = fcanon(x)
    return jnp.all(xc == 0, axis=-1)


def feq(a, b):
    return fiszero(fsub(a, b))


def fpow(x, bits: np.ndarray):
    """x^e for fixed public exponent given as msb-first bit array."""
    bits_j = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), x.shape)

    def body(i, acc):
        acc = fsq(acc)
        withmul = fmul(acc, x)
        return jnp.where((bits_j[i] == 1), withmul, acc)

    return lax.fori_loop(0, len(bits), body, one)


# ----------------------------------------------------- point arithmetic
# Extended twisted-Edwards coordinates (X, Y, Z, T), a = -1.

def pt_double(X, Y, Z, T):
    A = fsq(X)
    B = fsq(Y)
    C = fadd(fsq(Z), fsq(Z))
    E = fsub(fsub(fsq(fadd(X, Y)), A), B)
    G = fsub(B, A)
    F = fsub(G, C)
    H = fsub(fneg(A), B)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


def pt_add(X1, Y1, Z1, T1, X2, Y2, Z2, T2):
    A = fmul(fsub(Y1, X1), fsub(Y2, X2))
    B = fmul(fadd(Y1, X1), fadd(Y2, X2))
    C = fmul(fmul(T1, jnp.broadcast_to(jnp.asarray(_TWOD_L), T1.shape)), T2)
    Dv = fadd(fmul(Z1, Z2), fmul(Z1, Z2))
    E = fsub(B, A)
    F = fsub(Dv, C)
    G = fadd(Dv, C)
    H = fadd(B, A)
    return fmul(E, F), fmul(G, H), fmul(F, G), fmul(E, H)


def _select_pt(cond, pa, pb):
    c = cond[..., None]
    return tuple(jnp.where(c, a, b) for a, b in zip(pa, pb))


def decompress(ylimbs, sign):
    """(x, ok): recover x from y and sign bit; ok=False if not on curve."""
    yy = fsq(ylimbs)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), ylimbs.shape)
    u = fsub(yy, one)
    v = fadd(fmul(jnp.broadcast_to(jnp.asarray(_D_L), yy.shape), yy), one)
    v2 = fsq(v)
    v3 = fmul(v2, v)
    v7 = fmul(fsq(v3), v)
    x = fmul(fmul(u, v3), fpow(fmul(u, v7), _E58_BITS))
    vxx = fmul(v, fsq(x))
    is_root = feq(vxx, u)
    is_neg_root = fiszero(fadd(vxx, u))
    x = jnp.where((is_neg_root & ~is_root)[..., None],
                  fmul(x, jnp.broadcast_to(jnp.asarray(_SQRT_M1_L), x.shape)),
                  x)
    ok = is_root | is_neg_root
    xc = fcanon(x)
    x_zero = jnp.all(xc == 0, axis=-1)
    ok = ok & ~(x_zero & (sign == 1))
    parity = xc[..., 0] & 1
    x = jnp.where((parity != sign)[..., None], fneg(xc), xc)
    return x, ok


# ----------------------------------------------------- the verify kernel

def _base_point_ext() -> List[np.ndarray]:
    gy = G_Y_INT
    u = (gy * gy - 1) % P
    v = (D_INT * gy * gy + 1) % P
    gx = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * gx * gx - u) % P != 0:
        gx = gx * SQRT_M1_INT % P
    if gx & 1 != 0:
        gx = P - gx
    return [_int_to_limbs(gx), _int_to_limbs(gy), _int_to_limbs(1),
            _int_to_limbs(gx * gy % P)]


_B_EXT = _base_point_ext()


@jax.jit
def _verify_kernel(ay, asign, ry, rsign, s_words, k_words):
    """All inputs batched; returns bool[B].

    ay/ry: [B, 20] int32 limbs of the y coordinates (canonical, < p)
    asign/rsign: [B] int32 sign bits
    s_words/k_words: [B, 8] uint32 little-endian scalar words
    """
    ax, ok_a = decompress(ay, asign)
    rx, ok_r = decompress(ry, rsign)

    # -A in extended coordinates
    nax = fneg(ax)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), ay.shape)
    na_ext = (nax, ay, one, fmul(nax, ay))
    b_ext = tuple(jnp.broadcast_to(jnp.asarray(l), ay.shape) for l in _B_EXT)

    zero = jnp.zeros_like(ay)
    ident = (zero, one, one, zero)

    def body(i, st):
        st = pt_double(*st)
        j = 255 - i
        word = j // 32
        shift = j % 32
        sw = lax.dynamic_index_in_dim(s_words, word, axis=-1, keepdims=False)
        kw = lax.dynamic_index_in_dim(k_words, word, axis=-1, keepdims=False)
        sbit = (sw >> shift.astype(sw.dtype)) & 1
        kbit = (kw >> shift.astype(kw.dtype)) & 1
        st = _select_pt(sbit == 1, pt_add(*st, *b_ext), st)
        st = _select_pt(kbit == 1, pt_add(*st, *na_ext), st)
        return st

    X, Y, Z, _ = lax.fori_loop(0, 256, body, ident)

    ok_x = fiszero(fsub(fmul(rx, Z), X))
    ok_y = fiszero(fsub(fmul(ry, Z), Y))
    return ok_a & ok_r & ok_x & ok_y


# ----------------------------------------------------- host-side wrapper

def _pack_fe(values: Sequence[int]) -> np.ndarray:
    out = np.empty((len(values), NLIMB), dtype=np.int32)
    for i, v in enumerate(values):
        for k in range(NLIMB):
            out[i, k] = v & MASK
            v >>= RADIX
    return out


def _pack_words(values: Sequence[int]) -> np.ndarray:
    out = np.empty((len(values), 8), dtype=np.uint32)
    for i, v in enumerate(values):
        for k in range(8):
            out[i, k] = v & 0xFFFFFFFF
            v >>= 32
    return out


def host_pack(msgs: Sequence[bytes], sigs: Sequence[bytes],
              verkeys: Sequence[bytes]):
    """Host-side preprocessing: parse/canonicality-check sigs and keys,
    compute k = SHA-512(R||A||M) mod L (hashlib C core), pack limb arrays.

    → ([ay, asign, ry, rsign, s_words, k_words] host np arrays — the
    jit transfers them once; keeping them in numpy lets callers pad the
    batch axis without device round-trips — and valid bool[B])
    """
    n = len(msgs)
    assert len(sigs) == n and len(verkeys) == n
    ay, asign, ry, rsign, s_sc, k_sc = [], [], [], [], [], []
    valid = np.ones(n, dtype=bool)
    for i in range(n):
        sig, vk = sigs[i], verkeys[i]
        if len(sig) != 64 or len(vk) != 32:
            valid[i] = False
            sig, vk = b"\x00" * 64, b"\x01" + b"\x00" * 31
        a_int = int.from_bytes(vk, "little")
        r_int = int.from_bytes(sig[:32], "little")
        s_int = int.from_bytes(sig[32:], "little")
        ay_v, as_v = a_int & ((1 << 255) - 1), a_int >> 255
        ry_v, rs_v = r_int & ((1 << 255) - 1), r_int >> 255
        if ay_v >= P or ry_v >= P or s_int >= L:
            valid[i] = False
            ay_v = ry_v = 1
            as_v = rs_v = s_int = 0
        h = hashlib.sha512()
        h.update(sig[:32])
        h.update(vk)
        h.update(msgs[i])
        k_int = int.from_bytes(h.digest(), "little") % L
        ay.append(ay_v)
        asign.append(as_v)
        ry.append(ry_v)
        rsign.append(rs_v)
        s_sc.append(s_int)
        k_sc.append(k_int)
    arrays = [_pack_fe(ay),
              np.asarray(asign, np.int32),
              _pack_fe(ry),
              np.asarray(rsign, np.int32),
              _pack_words(s_sc),
              _pack_words(k_sc)]
    return arrays, valid


def verify_batch(msgs: Sequence[bytes], sigs: Sequence[bytes],
                 verkeys: Sequence[bytes]) -> np.ndarray:
    """Batched cofactorless ed25519 verify → np.bool_ array [B].

    Host does the cheap data-dependent prep (host_pack); the device does
    all elliptic-curve math in one dispatch.
    """
    ok_dev, valid, n = verify_batch_async(msgs, sigs, verkeys)
    if n == 0:
        return np.zeros(0, dtype=bool)
    return np.asarray(ok_dev)[:n] & valid


def verify_batch_async(msgs: Sequence[bytes], sigs: Sequence[bytes],
                       verkeys: Sequence[bytes]):
    """Non-blocking batched verify: enqueues the device computation and
    returns (ok_device_array, valid_host_bools, n) immediately — JAX
    dispatch is async, so the caller overlaps host work with the device
    round trip and materializes later (np.asarray(ok)[:n] & valid)."""
    n = len(msgs)
    if n == 0:
        return None, np.zeros(0, dtype=bool), 0
    arrays, valid = host_pack(msgs, sigs, verkeys)
    # pad the batch axis to the next power of two (min 8) by repeating
    # row 0 so every size in [1, 2^k] shares one compiled kernel —
    # variable pool queue depths must not trigger XLA recompiles
    padded = 8
    while padded < n:
        padded *= 2
    if padded != n:
        arrays = [np.concatenate(
            [a, np.repeat(a[:1], padded - n, axis=0)], axis=0)
            for a in arrays]
    ok = _verify_kernel(*arrays)
    return ok, valid, n
