"""Device-mesh crypto dispatch — shard the batch axis across every chip.

The crypto kernels in this package (ed25519 batch verify, BLS12-381
aggregation, batched SHA-256 / merkle gathers) are embarrassingly
parallel over their batch axis: every row is an independent signature,
aggregation job, leaf or proof index. That makes data parallelism over
the device mesh the cheapest untapped multiplier the framework has —
committee-consensus measurements (arXiv:2302.00418) put signature
verification on the ordering critical path, and hash-tree accelerators
(MTU, arXiv:2507.16793) win precisely by saturating parallel lanes.

This module is the ONE production seam for that axis:

 - `DeviceMesh` enumerates the available devices lazily (honoring
   ``JAX_PLATFORMS`` / ``xla_force_host_platform_device_count`` through
   JAX itself, capped by ``Config.MESH_MAX_DEVICES`` and rounded down to
   a power of two so bucket padding stays divisible).
 - `dispatch` pads a ragged batch to ``n_devices × per-device bucket``
   (power-of-two buckets, so every batch size in a bucket shares one
   compiled SPMD executable), places the arrays with a batch-axis
   ``NamedSharding``, and launches the jitted kernel asynchronously —
   the returned arrays are un-awaited device handles, so callers keep
   the same dispatch/collect overlap they had on one chip.  The kernels
   are row-wise pure, so XLA inserts ZERO collectives.
 - Passthrough: with ``MESH_ENABLED = False``, a single-device host, or
   a batch below ``Config.MESH_SHARD_MIN``, callers take their existing
   single-device path untouched (bench-gated to <5% overhead).
 - `MeshPipeline` double-buffers dispatch/collect across batches (the
   shape ``ops/merkle.ProofPipeline`` uses), keeping every chip's next
   batch enqueued while the host drains the previous download.
 - `probe_platform` / `is_accelerator` are the ONE lazy,
   exception-guarded "am I on a real accelerator?" probe — modules must
   route capability questions here instead of touching
   ``jax.devices()[0]`` directly (which force-initializes the backend).

Import of this module NEVER initializes JAX: server code (node
bootstrap, validator-info dumps) reads configuration and stats without
waking an accelerator; JAX loads on the first probe or dispatch.

Consumers: ``ops/ed25519_jax.verify_batch_async`` (and through it the
``CoalescingVerifierHub`` and the verify daemon), ``ops/bls381_jax``'s
batched aggregate path, and ``ops/merkle`` builds + proof gathers.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from plenum_tpu.observability.tracing import CAT_DEVICE, NullTracer
from plenum_tpu.observability import telemetry as _telemetry

# --------------------------------------------------------- capability probe

_PROBE_LOCK = threading.Lock()
_PROBE = {"platform": None, "device_count": None}


def probe_platform(default: str = "cpu") -> str:
    """Platform of device 0 ("cpu" / "tpu" / "gpu"), probed lazily and
    exception-guarded: a missing or broken backend reads as `default`
    instead of raising at import/dispatch time. First call initializes
    the JAX backend; every later call is a dict read."""
    with _PROBE_LOCK:
        if _PROBE["platform"] is None:
            try:
                import jax
                devs = jax.devices()
                _PROBE["platform"] = devs[0].platform
                _PROBE["device_count"] = len(devs)
            except Exception:  # plenum-lint: disable=PT006 — this IS
                # the package's designed guard: ANY broken/missing
                # backend must read as `default`, never raise
                _PROBE["platform"] = default
                _PROBE["device_count"] = 1
        return _PROBE["platform"]


def is_accelerator() -> bool:
    """True iff device 0 is a real accelerator (not the CPU backend)."""
    return probe_platform() not in ("cpu",)


def probed() -> bool:
    """Whether the backend has been probed THROUGH THIS MODULE already —
    lets status dumps report device facts without ever being the caller
    that wakes the backend."""
    return _PROBE["platform"] is not None


def _reset_probe() -> None:
    """Test hook: forget the cached probe result. Also clears the
    Pallas-backend availability cache below — both derive from the
    same platform probe, so a caller that re-probes (dryrun_multichip
    after un-pinning JAX_PLATFORMS) must re-decide Pallas too, or a
    stale "cpu" answer would disable the Pallas kernels process-wide
    on a real TPU."""
    with _PROBE_LOCK:
        _PROBE["platform"] = None
        _PROBE["device_count"] = None
        _PALLAS_BACKENDS.clear()


# ---------------------------------------------- pallas kernel availability

# env-var name -> bool; ONE probe-backed decision per kernel family
# (ed25519, sha256). Guarded by _PROBE_LOCK like the probe itself.
_PALLAS_BACKENDS = {}


def pallas_backend_enabled(env_var: str) -> bool:
    """THE availability gate every Pallas kernel consults (the ed25519
    whole-verify kernel and the SHA-256 compression kernel): enabled
    exactly when device 0 is a real accelerator, unless the kernel's
    env var pins ``"xla"``. Cached per kernel family so a permanent
    runtime failure (``disable_pallas_backend``) sticks; the cache is
    cleared together with the platform probe (``_reset_probe``)."""
    with _PROBE_LOCK:
        state = _PALLAS_BACKENDS.get(env_var)
    if state is None:
        state = (os.environ.get(env_var) != "xla") and is_accelerator()
        with _PROBE_LOCK:
            state = _PALLAS_BACKENDS.setdefault(env_var, state)
    return state


def xla_backend_enabled(env_var: str) -> bool:
    """Availability gate for device kernels written as plain XLA (the
    bls381 pairing/MSM path): these run on ANY backend — CPU included —
    so, unlike ``pallas_backend_enabled``, no accelerator is required.
    Enabled unless the kernel's env var pins the native/scalar path
    (``"native"``/``"off"``) or a runtime failure stepped it down
    (``disable_pallas_backend`` — same registry, same permanence)."""
    with _PROBE_LOCK:
        state = _PALLAS_BACKENDS.get(env_var)
    if state is None:
        state = os.environ.get(env_var, "").lower() \
            not in ("native", "off", "0")
        with _PROBE_LOCK:
            state = _PALLAS_BACKENDS.setdefault(env_var, state)
    return state


def disable_pallas_backend(env_var: str) -> None:
    """Permanent step-down for one kernel family — the fallback engine
    (ops/ed25519_jax._dispatch_kernel, ops/sha256 routing) calls this
    after an unrecoverable Pallas failure so every later dispatch goes
    straight to the XLA expression."""
    with _PROBE_LOCK:
        _PALLAS_BACKENDS[env_var] = False


def default_device():
    """Device 0 — the landing spot for single-device programs after a
    mesh-sharded build. The ONE sanctioned ``jax.devices()`` access
    besides the probe: callers (ops/merkle.py) must route through here
    so backend initialization stays observable via probed()."""
    import jax
    devs = jax.devices()
    with _PROBE_LOCK:
        if _PROBE["platform"] is None and devs:
            _PROBE["platform"] = devs[0].platform
            _PROBE["device_count"] = len(devs)
    return devs[0]


# ------------------------------------------------------------------ helpers

from plenum_tpu.ops import pow2_at_least as _pow2_at_least


def _pow2_at_most(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n >= 1 else 1


def pad_rows(arrays: Sequence, padded: int) -> List[np.ndarray]:
    """Pad the leading axis of every array to `padded` rows by repeating
    row 0. The mesh kernels are row-wise pure, so repeated rows only add
    redundant device work whose results the caller slices off — and
    repeating a REAL row (not zeros) keeps padding on the same code path
    the kernel already validated."""
    out = []
    for a in arrays:
        a = np.asarray(a)
        n = a.shape[0]
        if n == padded:
            out.append(a)
            continue
        reps = np.repeat(a[:1], padded - n, axis=0)
        out.append(np.concatenate([a, reps], axis=0))
    return out


# --------------------------------------------------------------- the mesh

class DeviceMesh:
    """Batch-axis sharding over the host's device mesh.

    Thread-safe: the verify daemon's worker thread and a node's prod
    loop may both dispatch; device enumeration and sharding construction
    are locked, counters are plain int bumps (GIL-atomic enough for
    stats).
    """

    def __init__(self, enabled: Optional[bool] = None,
                 max_devices: Optional[int] = None,
                 shard_min: Optional[int] = None,
                 min_per_device: int = 8,
                 cpu_shard: Optional[bool] = None):
        from plenum_tpu.common.config import Config
        self.enabled = Config.MESH_ENABLED if enabled is None else enabled
        self.max_devices = (Config.MESH_MAX_DEVICES
                            if max_devices is None else max_devices)
        self.shard_min = (Config.MESH_SHARD_MIN
                          if shard_min is None else shard_min)
        self.cpu_shard = (Config.MESH_CPU_SHARD
                          if cpu_shard is None else cpu_shard)
        self.min_per_device = min_per_device
        self.tracer = NullTracer()
        self._lock = threading.Lock()
        self._devices = None          # enumerated + capped device list
        self._sharding = None         # NamedSharding over axis "dp"
        self._replicated = None
        # stats (validator info / bench)
        self.dispatches = 0
        self.sharded_dispatches = 0
        self.passthrough_dispatches = 0
        self.last_batch = 0
        self.last_per_device = 0

    # ------------------------------------------------------ device facts

    def _init_devices_locked(self) -> None:
        if self._devices is not None:
            return
        try:
            import jax
            devs = list(jax.devices())
            with _PROBE_LOCK:
                if _PROBE["platform"] is None and devs:
                    _PROBE["platform"] = devs[0].platform
                    _PROBE["device_count"] = len(devs)
        except Exception:  # plenum-lint: disable=PT006 — same designed
            # guard as probe_platform: no backend reads as one device
            devs = []
        cap = self.max_devices if self.max_devices else len(devs)
        n = max(1, min(len(devs), cap))
        # power-of-two device counts keep per-device buckets divisible
        # and match real TPU topologies; a 6-chip cap uses 4
        self._devices = devs[:_pow2_at_most(n)]

    @property
    def devices(self) -> list:
        with self._lock:
            self._init_devices_locked()
            return list(self._devices)

    @property
    def n_devices(self) -> int:
        with self._lock:
            self._init_devices_locked()
            return max(1, len(self._devices))

    def reset_devices(self) -> None:
        """Re-enumerate on next use (tests / reconfiguration)."""
        with self._lock:
            self._devices = None
            self._sharding = None
            self._replicated = None

    # -------------------------------------------------------- shardings

    def sharding(self):
        """NamedSharding that splits the leading (batch) axis over the
        mesh and replicates every other axis."""
        with self._lock:
            self._init_devices_locked()
            if self._sharding is None:
                from jax.sharding import Mesh, NamedSharding, PartitionSpec
                mesh = Mesh(np.array(self._devices), axis_names=("dp",))
                self._sharding = NamedSharding(mesh, PartitionSpec("dp"))
                self._replicated = NamedSharding(mesh, PartitionSpec())
            return self._sharding

    def replicated(self):
        """NamedSharding that replicates an array on every mesh device
        (read-shared operands of index-sharded gathers)."""
        self.sharding()
        return self._replicated

    # -------------------------------------------------------- dispatch

    def should_shard(self, n: int) -> bool:
        """The passthrough gate: shard only when the mesh is enabled,
        more than one chip is present, the batch clears MESH_SHARD_MIN
        (below it, sharding overhead exceeds the win), AND the devices
        are real accelerators — XLA's virtual CPU devices share the
        physical cores, so sharding over them only adds partition
        overhead (the BENCH_r05 merkle-build collapse). Tests and
        dryrun_multichip force the CPU-sharded paths via cpu_shard /
        PLENUM_TPU_MESH_CPU_SHARD=1 (env, so spawned node processes
        inherit it)."""
        if not self.enabled or n < self.shard_min:
            return False
        if self.n_devices <= 1:
            return False
        return (is_accelerator() or self.cpu_shard
                or os.environ.get("PLENUM_TPU_MESH_CPU_SHARD") == "1")

    def padded_size(self, n: int, min_per_device: Optional[int] = None
                    ) -> int:
        """Smallest n_devices × (power-of-two per-device bucket) that
        holds n rows — every batch size inside a bucket shares ONE
        compiled SPMD executable, so variable queue depths never hit a
        fresh XLA compile mid-run."""
        d = self.n_devices
        mpd = self.min_per_device if min_per_device is None \
            else min_per_device
        per = _pow2_at_least(max(mpd, -(-n // d)))
        return per * d

    def put_sharded(self, arrays: Sequence) -> list:
        """Place already-padded arrays with the batch-axis sharding."""
        import jax
        sh = self.sharding()
        return [jax.device_put(a, sh) for a in arrays]

    def dispatch(self, fn: Callable, arrays: Sequence, n: Optional[int]
                 = None, label: str = "mesh_dispatch"):
        """Shard `arrays` (leading axis already padded to padded_size)
        over the mesh and launch the jitted `fn` asynchronously.

        Returns fn's un-awaited output arrays — JAX dispatch is async,
        so the caller overlaps host work with all chips' round trips
        and materializes later (np.asarray). The span + counters feed
        the flight recorder: per-device batch size is the number that
        says whether the mesh actually spread the work."""
        b = int(np.shape(arrays[0])[0])
        d = self.n_devices
        per = b // d
        # lane accounting: every padded row is a launched-but-wasted
        # device lane; the (padded, devices) pair is the SPMD compile
        # shape, so a new one is a compile event
        _telemetry.get_seam_hub().record_launch(
            _telemetry.SEAM_MESH, b if n is None else n, b, shape=(b, d))
        with self.tracer.span(label, CAT_DEVICE, n=b if n is None else n,
                              padded=b, devices=d, per_device=per):
            outs = fn(*self.put_sharded(arrays))
        self.dispatches += 1
        self.sharded_dispatches += 1
        self.last_batch = b
        self.last_per_device = per
        self.tracer.counter("mesh_devices", d)
        self.tracer.counter("mesh_per_device_batch", per)
        return outs

    def note_passthrough(self, n: int) -> None:
        """Bookkeeping for a dispatch that took the single-device path
        (counted so validator info shows the gate working)."""
        self.dispatches += 1
        self.passthrough_dispatches += 1
        self.last_batch = n

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """Snapshot for ValidatorNodeInfoTool / bench. Never initializes
        a backend: device facts appear only once something already
        enumerated the mesh (or probed the platform)."""
        out = {
            "enabled": self.enabled,
            "max_devices": self.max_devices,
            "shard_min": self.shard_min,
            "cpu_shard": self.cpu_shard,
            "dispatches": self.dispatches,
            "sharded_dispatches": self.sharded_dispatches,
            "passthrough_dispatches": self.passthrough_dispatches,
            "last_batch": self.last_batch,
            "last_per_device_batch": self.last_per_device,
        }
        if self._devices is not None:
            out["n_devices"] = len(self._devices)
        if probed():
            out["platform"] = _PROBE["platform"]
            out["host_device_count"] = _PROBE["device_count"]
        return out


# ----------------------------------------------------------- pipelining

class MeshPipeline:
    """Depth-bounded dispatch/collect streamer over mesh dispatches —
    the same per-device double-buffering shape as
    ops/merkle.ProofPipeline: up to `depth` sharded launches stay in
    flight, so every chip's next batch is already enqueued while the
    host materializes the previous results. Used by the MULTICHIP
    harness (__graft_entry__) and available to any dispatch/collect
    pair (the merkle and verify seams keep their specialized
    pipelines)."""

    def __init__(self, dispatch_fn: Callable, collect_fn: Callable,
                 depth: int = 2, tracer=None):
        self._dispatch = dispatch_fn
        self._collect = collect_fn
        self._depth = max(1, depth)
        self._tracer = tracer or NullTracer()

    def stream(self, batches):
        from collections import deque
        pending = deque()
        tracer = self._tracer
        for batch in batches:
            with tracer.span("mesh_pipe_dispatch", CAT_DEVICE):
                pending.append(self._dispatch(batch))
            tracer.counter("mesh_pipe_inflight", len(pending))
            if len(pending) >= self._depth:
                with tracer.span("mesh_pipe_collect", CAT_DEVICE):
                    out = self._collect(pending.popleft())
                yield out
        while pending:
            with tracer.span("mesh_pipe_collect", CAT_DEVICE):
                out = self._collect(pending.popleft())
            yield out

    def run(self, batches) -> list:
        return list(self.stream(batches))


# ----------------------------------------------------- process singleton

_MESH: Optional[DeviceMesh] = None
_MESH_LOCK = threading.Lock()


def get_mesh() -> DeviceMesh:
    """The process-wide mesh every dispatch seam consults. Constructed
    lazily from Config class defaults; node bootstrap / bench / tests
    reconfigure it via configure()/configure_from()."""
    global _MESH
    with _MESH_LOCK:
        if _MESH is None:
            _MESH = DeviceMesh()
        return _MESH


def configure(enabled: Optional[bool] = None,
              max_devices: Optional[int] = None,
              shard_min: Optional[int] = None,
              tracer=None,
              cpu_shard: Optional[bool] = None) -> DeviceMesh:
    """Reconfigure the process-wide mesh. Changing the device cap resets
    the enumeration (and compiled-sharding cache) so the next dispatch
    sees the new mesh shape."""
    m = get_mesh()
    if enabled is not None:
        m.enabled = enabled
    if shard_min is not None:
        m.shard_min = shard_min
    if cpu_shard is not None:
        m.cpu_shard = cpu_shard
    if max_devices is not None and max_devices != m.max_devices:
        m.max_devices = max_devices
        m.reset_devices()
    if tracer is not None:
        m.tracer = tracer
    return m


def configure_from(config) -> DeviceMesh:
    """Apply a Config instance's MESH_* knobs (node bootstrap seam)."""
    return configure(
        enabled=getattr(config, "MESH_ENABLED", None),
        max_devices=getattr(config, "MESH_MAX_DEVICES", None),
        shard_min=getattr(config, "MESH_SHARD_MIN", None),
        cpu_shard=getattr(config, "MESH_CPU_SHARD", None))


def mesh_stats() -> dict:
    """Stats for status dumps; safe to call from paths that must never
    initialize a device runtime."""
    with _MESH_LOCK:
        m = _MESH
    return m.stats() if m is not None else {"enabled": None}
