"""Batched MPT node hashing on device — the trie's dispatch seam.

The state engine (state/device_state.py) decomposes many key walks /
a whole batch's dirty-node writes into LEVELS of independent node
blobs; every level becomes ONE device SHA3-256 dispatch through this
module. Two fused programs:

 - ``dispatch_node_hash_batch`` / ``collect_node_hash_batch``: hash a
   level of RLP node blobs; digests come back as one [B, 32] uint8
   buffer (apply path — the digests become the child refs of the next
   level up).
 - ``dispatch_node_verify_batch`` / ``collect_node_verify_batch``:
   hash AND compare against expected refs in the same program; only a
   [B] bool verdict crosses back (read/proof path — re-verifying node
   integrity while serving, so a corrupted store can never serve a
   value or proof that does not hash to its ref).

Batches clearing the mesh gate (ops/mesh.py) shard the batch axis over
every chip — each row is an independent Keccak absorb, so the SPMD
program has zero collectives; smaller batches keep the single-device
path, and the gate below them is the caller's (Config
STATE_DEVICE_BATCH_MIN routes tiny batches to hashlib on host).

The merged multi-state resolver (state/device_state.resolve_applies,
conflict-lane executor) is the third caller: it concatenates level N
of EVERY state trie a batch wrote into one ``hash_nodes`` launch, so
a mixed domain+pool+config batch pays one dispatch per level total,
not one per state — the batch axis does the merging, this module
needs no new shapes. Its routing gate is its own
(Config.EXEC_MERGED_DEVICE_HASH: device only on real accelerators),
because at MPT node counts hashlib beats per-level dispatch overhead
on CPU hosts.
"""
from __future__ import annotations

import functools
from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from plenum_tpu.observability import telemetry as _tmy
from plenum_tpu.ops import pow2_at_least as _pow2_at_least
from plenum_tpu.ops.sha3 import (
    _sha3_blocks, digests_to_array, digests_to_bytes, pad_sha3_messages)


def _get_mesh():
    from plenum_tpu.ops import mesh as mesh_mod
    return mesh_mod.get_mesh()


def _record_level_lanes(b: int, bp: int, nblocks: int) -> None:
    """Lane accounting for one MPT level dispatch: b real node blobs
    launched on bp batch lanes (power-of-two / mesh bucket); the
    (bp, nblocks) pair is the compile-relevant Keccak shape."""
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_TRIE, b, bp, shape=(bp, nblocks))


def _pad_single(arrays, b: int):
    """Pad the batch axis to a power of two on the single-device path —
    level sizes vary per call, and an unbucketed batch dimension would
    pay a fresh XLA compile of the Keccak kernel per distinct size
    (the same bound ops/merkle.py enforces). Padding repeats row 0, so
    the extra rows are valid work whose results the collect slices off."""
    from plenum_tpu.ops.mesh import pad_rows
    bp = _pow2_at_least(b)
    return arrays if bp == b else pad_rows(arrays, bp)


@functools.partial(jax.jit, static_argnames=("nblocks",))
def _sha3_blocks_eq(blocks, nvalid, expected_u8, nblocks: int):
    """Fused hash + compare: → [B] bool, True where the SHA3-256 of the
    message equals the expected 32-byte ref. The digest never leaves
    the device — only the verdict does."""
    dig = _sha3_blocks(blocks, nvalid, nblocks)  # [B, 8] u32, LE words
    w = expected_u8.reshape(expected_u8.shape[0], 8, 4).astype(jnp.uint32)
    exp = (w[..., 0] | w[..., 1] << 8 | w[..., 2] << 16 | w[..., 3] << 24)
    return jnp.all(dig == exp, axis=-1)


def dispatch_node_hash_batch(blobs: Sequence[bytes]):
    """Start the device SHA3-256 of one level of node blobs; pair with
    collect_node_hash_batch (the dispatch is async — the caller builds
    the next level's host work while the device hashes this one)."""
    b = len(blobs)
    if b == 0:
        return (None, 0)
    words, nvalid, nblocks = pad_sha3_messages(blobs)
    dm = _get_mesh()
    if dm.should_shard(b):
        from plenum_tpu.ops.mesh import pad_rows
        bp = dm.padded_size(b)
        _record_level_lanes(b, bp, nblocks)
        w, nv = pad_rows([words, nvalid], bp)
        dig = dm.dispatch(
            lambda ww, nn: _sha3_blocks(ww, nn, nblocks), [w, nv],
            n=b, label="state_sha3")
    else:
        dm.note_passthrough(b)
        _record_level_lanes(b, _pow2_at_least(b), nblocks)
        words, nvalid = _pad_single([words, nvalid], b)
        dig = _sha3_blocks(jnp.asarray(words), jnp.asarray(nvalid),
                           nblocks)
    return (dig, b)


def collect_node_hash_batch(handle) -> np.ndarray:
    """Await a dispatch_node_hash_batch handle → [B, 32] u8 digests."""
    dig, b = handle
    if b == 0:
        return np.zeros((0, 32), dtype=np.uint8)
    return digests_to_array(np.asarray(dig)[:b])


def dispatch_node_verify_batch(blobs: Sequence[bytes],
                               expected: Sequence[bytes]):
    """Start the fused hash+compare of node blobs against their 32-byte
    refs; pair with collect_node_verify_batch."""
    b = len(blobs)
    if b == 0:
        return (None, 0)
    words, nvalid, nblocks = pad_sha3_messages(blobs)
    exp = np.frombuffer(b"".join(expected), dtype=np.uint8).reshape(b, 32)
    dm = _get_mesh()
    if dm.should_shard(b):
        from plenum_tpu.ops.mesh import pad_rows
        bp = dm.padded_size(b)
        _record_level_lanes(b, bp, nblocks)
        w, nv, e = pad_rows([words, nvalid, exp], bp)
        ok = dm.dispatch(
            lambda ww, nn, ee: _sha3_blocks_eq(ww, nn, ee, nblocks),
            [w, nv, e], n=b, label="state_sha3_verify")
    else:
        dm.note_passthrough(b)
        _record_level_lanes(b, _pow2_at_least(b), nblocks)
        words, nvalid, exp = _pad_single([words, nvalid, exp], b)
        ok = _sha3_blocks_eq(jnp.asarray(words), jnp.asarray(nvalid),
                             jnp.asarray(exp), nblocks)
    return (ok, b)


def collect_node_verify_batch(handle) -> np.ndarray:
    """Await a dispatch_node_verify_batch handle → [B] bool verdicts."""
    ok, b = handle
    if b == 0:
        return np.zeros((0,), dtype=bool)
    return np.asarray(ok)[:b]


def hash_nodes(blobs: Sequence[bytes]) -> List[bytes]:
    """Synchronous convenience: SHA3-256 every blob in one dispatch."""
    dig, b = dispatch_node_hash_batch(blobs)
    if b == 0:
        return []
    return digests_to_bytes(np.asarray(dig)[:b])
