"""BLS12-381 tower-field arithmetic (Fp2/Fp6/Fp12) on the u32-limb
Montgomery representation of ops/bls381_jax.py — the field layer under
the device pairing kernels (ops/bls381_pairing.py).

Layout
------
An Fq element is [..., 32] int32 limbs, radix 2^12, Montgomery domain
(same as bls381_jax). The tower stacks coefficients on extra axes:

 - Fp2  : [..., 2, 32]      (c0 + c1·u,  u^2 = -1)
 - Fp6  : [..., 3, 2, 32]   (e0 + e1·v + e2·v^2,  v^3 = ξ = 1+u)
 - Fp12 : [..., 12, 32]     (flattened [6, 2, 32]: fp2 slot s = 3i+j
                             for coefficient c_i.e_j of c0 + c1·w,
                             w^2 = v)

The whole point of the stacking: one Fp12 multiply issues ONE
`mont_mul` call over 18 Karatsuba fp2-lanes (= 54 Fq lanes), not 54
separate 32-limb multiplies — `mont_mul` broadcasts over every leading
axis, so the fold-matmul and the 32-step REDC amortize across lanes,
batch and pair axes in a single fused HLO region. On the v5e that is
the difference between a Miller loop that is VPU-bound and one that is
dispatch-bound; on CPU it divides XLA compile time by the lane count.

Bound-tracked relaxed arithmetic
--------------------------------
Karatsuba needs sums and differences BETWEEN multiplies, and a full
carry-normalize (`_carry_seq`, 32 unrolled steps) after each one would
cost as much as the multiply. Instead every traced value carries a
static Python-side bound (units of q) in a `TV` wrapper:

 - limbs stay in [0, 4099) (one parallel carry round after add/sub),
 - value < bound·q with bound <= 8,
 - `_norm` inserts conditional subtracts of 4q/2q/q exactly where a
   consumer's precondition requires it (mont_mul inputs < 4q, subtract
   operands < 2q, fp2 equality canonical).

The bounds are Python floats resolved at TRACE time, so the inserted
normalizations are deterministic per compiled shape — the device graph
is branchless. `_rsub` avoids the sequential borrow chain entirely by
adding a redundant-limb representation of 4q (`_SUBPAD`, every limb
big enough to absorb any subtrahend limb) and doing one parallel carry
round: 5 elementwise HLO ops instead of ~100.

Correctness of the discipline rests on three checked facts (asserted
at import against exact integer arithmetic):
 1. q/2^384 < 0.102, so mont_mul on inputs < 4q yields < 2.64q.
 2. A value < 8q has top limb <= 3330, so parallel-carry adds of
    bound-sum <= 8 never overflow the (dropped-carry) top column.
 3. `_SUBPAD` limbs are >= 4098 below the top (every relaxed limb is
    <= 4098) and its top limb dominates any < 2q subtrahend's.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from plenum_tpu.ops.bls381_jax import (
    MASK, NLIMB, Q, RADIX, R_MONT,
    _carry_par, _carry_seq, _cond_sub, _exp_bits, _geq, _int_to_limbs,
    _HALF_P1_L, _ONE_M_L, _Q_L, _R2_L, _2Q_L,
    fpow, limbs_to_int, mont_mul)

# ---------------------------------------------------------------- constants

_4Q_L = _int_to_limbs(4 * Q)
# mont_mul output bound factor: out < (a·b)/2^384 + q for inputs a, b
_QR = 0.102
assert Q / R_MONT < _QR


def _mont_l(v: int) -> np.ndarray:
    return _int_to_limbs(v * R_MONT % Q)


def _build_subpad() -> np.ndarray:
    """4q in a redundant-limb form: limbs[i] >= 4098 for i < 31 (any
    relaxed limb is <= 4098, so per-column x + pad - y never borrows)
    and a top limb that still dominates a < 2q subtrahend's top limb.
    Built by borrowing 2^12-units downward from the top."""
    limbs = [int(v) for v in _int_to_limbs(4 * Q)]
    for i in range(30, -1, -1):
        while limbs[i] < 4100:
            limbs[i] += 1 << RADIX
            limbs[i + 1] -= 1
    assert sum(l << (RADIX * i) for i, l in enumerate(limbs)) == 4 * Q
    assert all(4098 <= l <= 4100 + MASK for l in limbs[:31])
    # top limb must cover a < 2q subtrahend's top limb (<= 841) and
    # keep the top COLUMN of x + pad - y under 2^12 for x < 4q
    assert 850 <= limbs[31] <= 1700
    return np.array(limbs, dtype=np.int32)


_SUBPAD = _build_subpad()

# top-limb ceiling per unit of q: value < b·q  =>  limb31 <= _TOPL·b
_TOPL = (Q >> (RADIX * 31)) + 3
assert _TOPL * 8 < (1 << RADIX)                      # _radd, bound-sum 8
assert _TOPL * 4 + _SUBPAD[31] < (1 << RADIX)        # _rsub, x < 4q
assert _SUBPAD[31] >= _TOPL * 2                      # _rsub, y < 2q


class TV:
    """A traced field value with a static magnitude bound (units of q).
    `a` is the limb array ([..., 32] trailing); `b` the bound. Shapes
    and bounds are Python-side, so all normalization decisions resolve
    at trace time."""
    __slots__ = ("a", "b")

    def __init__(self, a, b: float):
        assert b <= 8.0, f"tower value bound {b} exceeds 8q invariant"
        self.a = a
        self.b = b


def _norm(x: TV, limit: float) -> TV:
    """Conditionally subtract multiples of q until value < limit·q."""
    a, b = x.a, x.b
    while b > limit:
        if b > 4.0:
            a = _cond_sub(a, _4Q_L)
            b = max(4.0, b - 4.0)
        elif b > 2.0:
            a = _cond_sub(a, _2Q_L)
            b = max(2.0, b - 2.0)
        else:
            a = _cond_sub(a, _Q_L)
            b = 1.0
    return TV(a, b)


def _radd(x: TV, y: TV) -> TV:
    """Relaxed add: one parallel carry round (4 HLO ops). Inputs are
    normalized so the bound-sum stays <= 8 (top-column safety)."""
    while x.b + y.b > 8.0:
        if x.b >= y.b:
            x = _norm(x, 2.0)
        else:
            y = _norm(y, 2.0)
    return TV(_carry_par(x.a + y.a), x.b + y.b)


def _rsub(x: TV, y: TV) -> TV:
    """Relaxed subtract via the borrow-proof 4q pad: x + 4q - y with
    one parallel carry round. Needs y < 2q (limb-dominated by the pad)
    and x < 4q (top-column safety); result < (x.b + 4)·q."""
    x = _norm(x, 4.0)
    y = _norm(y, 2.0)
    return TV(_carry_par(x.a + jnp.asarray(_SUBPAD) - y.a), x.b + 4.0)


def tmul(x: TV, y: TV) -> TV:
    """Montgomery product with bound tracking; output < 2q."""
    x = _norm(x, 4.0)
    y = _norm(y, 4.0)
    raw = TV(mont_mul(x.a, y.a), x.b * y.b * _QR + 1.0)
    return _norm(raw, 2.0)


def tneg(x: TV) -> TV:
    return _rsub(TV(jnp.zeros_like(x.a), 0.0), x)


def tcanon(x: TV):
    """Exact canonical limbs in [0, q) — for equality/compare only."""
    v = _norm(TV(_carry_seq(x.a), x.b), 2.0)
    return _cond_sub(v.a, _Q_L)


def _tstack(tvs: Sequence[TV], axis: int) -> TV:
    return TV(jnp.stack([t.a for t in tvs], axis=axis),
              max(t.b for t in tvs))


def _tcat(tvs: Sequence[TV], axis: int) -> TV:
    return TV(jnp.concatenate([t.a for t in tvs], axis=axis),
              max(t.b for t in tvs))


# ---------------------------------------------------------------- Fp2
#
# Value layout [..., 2, 32]; a lane axis for stacked multiplies sits at
# -3 ([..., S, 2, 32]). ξ = 1 + u is the cubic/sextic non-residue.

_ONE2_M = np.stack([_ONE_M_L, np.zeros(NLIMB, np.int32)])
_NEG1_M = _mont_l(Q - 1)
_B_TWIST_M = np.stack([_mont_l(4), _mont_l(4)])        # E': y^2=x^3+4(1+u)
_B3_TWIST_M = np.stack([_mont_l(12), _mont_l(12)])
_SQRT34_BITS = _exp_bits((Q - 3) // 4)
_HALFQ_BITS = _exp_bits((Q - 1) // 2)


def _c0(x: TV) -> TV:
    return TV(x.a[..., 0, :], x.b)


def _c1(x: TV) -> TV:
    return TV(x.a[..., 1, :], x.b)


def fp2_mul_many(x: TV, y: TV) -> TV:
    """Karatsuba fp2 product over any stacked shape [..., 2, 32]:
    exactly ONE mont_mul call on 3 stacked Fq lanes per fp2 lane."""
    x = _norm(x, 2.0)
    y = _norm(y, 2.0)
    a0, a1 = _c0(x), _c1(x)
    b0, b1 = _c0(y), _c1(y)
    left = _tstack([a0, a1, _radd(a0, a1)], -2)
    right = _tstack([b0, b1, _radd(b0, b1)], -2)
    p = tmul(left, right)                       # [..., 3, 32]
    t0, t1, tc = (TV(p.a[..., k, :], p.b) for k in range(3))
    r0 = _rsub(t0, t1)                          # a0·b0 - a1·b1
    r1 = _rsub(tc, _radd(t0, t1))               # cross - t0 - t1
    return _tstack([r0, r1], -2)


def fp2_mul(x: TV, y: TV) -> TV:
    """fp2 product normalized back to loop-normal form (< 2q)."""
    return _norm(fp2_mul_many(x, y), 2.0)


def fp2_add(x: TV, y: TV) -> TV:
    return _radd(x, y)


def fp2_sub(x: TV, y: TV) -> TV:
    return _rsub(x, y)


def fp2_neg(x: TV) -> TV:
    return tneg(x)


def fp2_mul_xi(x: TV) -> TV:
    """Multiply by ξ = 1 + u: (c0 - c1) + (c0 + c1)·u."""
    x = _norm(x, 2.0)
    a, b = _c0(x), _c1(x)
    return _tstack([_rsub(a, b), _radd(a, b)], -2)


def fp2_conj(x: TV) -> TV:
    x = _norm(x, 2.0)
    return _tstack([_c0(x), tneg(_c1(x))], -2)


def fp2_canon(x: TV):
    return tcanon(x)


def fp2_eq(x: TV, y: TV):
    return jnp.all(tcanon(x) == tcanon(y), axis=(-2, -1))


def fp2_is_zero(x: TV):
    return jnp.all(tcanon(x) == 0, axis=(-2, -1))


def fp2_pow(x: TV, bits: np.ndarray) -> TV:
    """x^e for a fixed msb-first public exponent; one fori_loop whose
    body is two stacked fp2 multiplies (square + conditional mul)."""
    x = _norm(x, 2.0)
    bits_j = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(_ONE2_M), x.a.shape)

    def body(i, acc):
        sq = fp2_mul(TV(acc, 2.0), TV(acc, 2.0))
        m = fp2_mul(sq, TV(x.a, x.b))
        return jnp.where(bits_j[i] == 1, m.a, sq.a)

    return TV(lax.fori_loop(0, len(bits), body, one), 2.0)


def fp2_inv(x: TV) -> TV:
    """(c0 - c1·u) / (c0^2 + c1^2); the Fq inversion is a fixed
    fpow(q-2) chain. Zero maps to zero (garbage-in tolerated: callers
    gate on a validity mask, never on a trap)."""
    x = _norm(x, 2.0)
    a, b = _c0(x), _c1(x)
    n = _norm(_radd(tmul(a, a), tmul(b, b)), 2.0)
    ni = TV(fpow(n.a, _INV_BITS), 2.0)
    return _tstack([tmul(a, ni), tneg(tmul(b, ni))], -2)


_INV_BITS = _exp_bits(Q - 2)


def fp2_sqrt(x: TV) -> Tuple[TV, jnp.ndarray]:
    """Square root in Fp2 for q ≡ 3 (mod 4) (same algorithm as the
    scalar reference `Fq2.sqrt`). Returns (root, ok[...]); ok is False
    for non-residues (the root array is then garbage, masked off by
    the caller). Cost: two fixed-exponent fp2 power loops."""
    x = _norm(x, 2.0)
    a1 = fp2_pow(x, _SQRT34_BITS)                    # x^((q-3)/4)
    alpha = fp2_mul(fp2_mul(a1, a1), x)              # a1^2 · x
    x0 = fp2_mul(a1, x)                              # a1 · x
    # alpha == -1  ->  root is u·x0 = (-x0.c1, x0.c0)
    neg1 = jnp.concatenate(
        [jnp.broadcast_to(jnp.asarray(_NEG1_M), x0.a[..., :1, :].shape),
         jnp.zeros_like(x0.a[..., :1, :])], axis=-2)
    is_neg1 = fp2_eq(alpha, TV(neg1, 1.0))
    ux0 = _norm(_tstack([tneg(_c1(x0)), _c0(x0)], -2), 2.0)
    one2 = jnp.broadcast_to(jnp.asarray(_ONE2_M), x0.a.shape)
    t = _radd(alpha, TV(one2, 1.0))
    cand = fp2_mul(fp2_pow(t, _HALFQ_BITS), x0)
    root = TV(jnp.where(is_neg1[..., None, None], ux0.a, cand.a), 2.0)
    ok = fp2_eq(fp2_mul(root, root), x)
    return root, ok


# ---------------------------------------------------------------- Fp6
#
# Only the operations the inversion chain needs run at fp6 granularity
# (one final-exp easy part per batch); the Miller-loop hot path goes
# straight to the 18-lane fp12 multiply below.

def _fp6c(x: TV, k: int) -> TV:
    return TV(x.a[..., k, :, :], x.b)


def fp6_mul_xi(x: TV) -> TV:
    """v-multiplication: (e0, e1, e2) -> (ξ·e2, e0, e1)."""
    x = _norm(x, 2.0)
    return _tstack([_norm(fp2_mul_xi(_fp6c(x, 2)), 2.0),
                    _fp6c(x, 0), _fp6c(x, 1)], -3)


def fp6_mul(x: TV, y: TV) -> TV:
    """Karatsuba fp6 product: one 6-lane stacked fp2 multiply."""
    x = _norm(x, 2.0)
    y = _norm(y, 2.0)
    a = [_fp6c(x, k) for k in range(3)]
    b = [_fp6c(y, k) for k in range(3)]
    left = _tstack(a + [_radd(a[1], a[2]), _radd(a[0], a[1]),
                        _radd(a[0], a[2])], -3)
    right = _tstack(b + [_radd(b[1], b[2]), _radd(b[0], b[1]),
                         _radd(b[0], b[2])], -3)
    p = fp2_mul_many(left, right)               # [..., 6, 2, 32]
    t0, t1, t2, s0, s1, s2 = (TV(p.a[..., k, :, :], p.b)
                              for k in range(6))
    c0 = _radd(_norm(fp2_mul_xi(_rsub(s0, _radd(t1, t2))), 2.0), t0)
    c1 = _radd(_rsub(s1, _radd(t0, t1)), _norm(fp2_mul_xi(t2), 2.0))
    c2 = _radd(_rsub(s2, _radd(t0, t2)), t1)
    return _tstack([_norm(c0, 2.0), _norm(c1, 2.0), _norm(c2, 2.0)],
                   -3)


def fp6_inv(x: TV) -> TV:
    """Reference `Fq6.inv` ported term for term."""
    x = _norm(x, 2.0)
    a0, a1, a2 = (_fp6c(x, k) for k in range(3))
    t0 = _rsub(fp2_mul(a0, a0), fp2_mul_xi(fp2_mul(a1, a2)))
    t1 = _rsub(fp2_mul_xi(fp2_mul(a2, a2)), fp2_mul(a0, a1))
    t2 = _rsub(fp2_mul(a1, a1), fp2_mul(a0, a2))
    den = fp2_add(
        fp2_mul(a0, t0),
        fp2_mul_xi(fp2_add(fp2_mul(a2, t1), fp2_mul(a1, t2))))
    di = fp2_inv(_norm(den, 2.0))
    return _tstack([fp2_mul(t0, di), fp2_mul(t1, di), fp2_mul(t2, di)],
                   -3)


# ---------------------------------------------------------------- Fp12
#
# Flat [..., 12, 32]; fp2 slot s = 3i + j holds coefficient c_i.e_j.
# The w-power of slot s is k = i + 2j (w^2 = v, w^6 = ξ) — the order
# the Frobenius constant table is laid out in.

_ONE12_M = np.zeros((12, NLIMB), np.int32)
_ONE12_M[0] = _ONE_M_L


def _as6(x: TV) -> TV:
    """[..., 12, 32] -> [..., 6, 2, 32] fp2-slot view."""
    return TV(x.a.reshape(x.a.shape[:-2] + (6, 2, NLIMB)), x.b)


def _as12(x: TV) -> TV:
    return TV(x.a.reshape(x.a.shape[:-3] + (12, NLIMB)), x.b)


def fp12_one(shape: Tuple[int, ...]) -> jnp.ndarray:
    return jnp.broadcast_to(jnp.asarray(_ONE12_M),
                            tuple(shape) + (12, NLIMB))


def fp12_mul(x: TV, y: TV) -> TV:
    """Full fp12 product as ONE 18-lane stacked fp2 multiply: three
    Karatsuba fp6 products (c0·d0, c1·d1, (c0+c1)(d0+d1)), each itself
    6 Karatsuba fp2 lanes, evaluated in a single mont_mul launch."""
    xs = _norm(_as6(x), 2.0)
    ys = _norm(_as6(y), 2.0)
    lanes_l: List[TV] = []
    lanes_r: List[TV] = []
    for src, lanes in ((xs, lanes_l), (ys, lanes_r)):
        h0 = TV(src.a[..., 0:3, :, :], src.b)
        h1 = TV(src.a[..., 3:6, :, :], src.b)
        hs = _radd(h0, h1)                       # fp6 half-sum
        for g in (h0, h1, hs):
            a = [TV(g.a[..., k, :, :], g.b) for k in range(3)]
            lanes.append(_tstack(
                a + [_radd(a[1], a[2]), _radd(a[0], a[1]),
                     _radd(a[0], a[2])], -3))
    left = _tcat(lanes_l, -3)                    # [..., 18, 2, 32]
    right = _tcat(lanes_r, -3)
    p = fp2_mul_many(left, right)
    pg = TV(p.a.reshape(p.a.shape[:-3] + (3, 6, 2, NLIMB)), p.b)
    # fp6 combine, vectorized over the 3 product groups
    t0, t1, t2, s0, s1, s2 = (TV(pg.a[..., k, :, :], pg.b)
                              for k in range(6))
    c0 = _radd(_norm(fp2_mul_xi(_rsub(s0, _radd(t1, t2))), 2.0), t0)
    c1 = _radd(_rsub(s1, _radd(t0, t1)), _norm(fp2_mul_xi(t2), 2.0))
    c2 = _radd(_rsub(s2, _radd(t0, t2)), t1)
    v = _tstack([_norm(c0, 2.0), _norm(c1, 2.0), _norm(c2, 2.0)], -3)
    # v: [..., 3(group), 3(coeff), 2, 32] -> fp12 combine
    v0, v1, v2 = (TV(v.a[..., g, :, :, :], v.b) for g in range(3))
    r0 = _radd(v0, fp6_mul_xi(v1))               # c0·d0 + v·(c1·d1)
    r1 = _rsub(v2, _radd(v0, v1))                # cross - both
    out = _tcat([_norm(r0, 2.0), _norm(r1, 2.0)], -3)
    return _as12(out)


def fp12_sq(x: TV) -> TV:
    return fp12_mul(x, x)


def fp12_conj(x: TV) -> TV:
    """x -> x^(q^6): negate the c1 (odd w-power) half."""
    xs = _norm(_as6(x), 2.0)
    h0 = TV(xs.a[..., 0:3, :, :], xs.b)
    h1 = _norm(tneg(TV(xs.a[..., 3:6, :, :], xs.b)), 2.0)
    return _as12(_tcat([h0, h1], -3))


def fp12_inv(x: TV) -> TV:
    """Reference `Fq12.inv`: (c0^2 - v·c1^2)^-1 through the fp6/fp2
    inversion chain. One call per final exponentiation."""
    xs = _norm(_as6(x), 2.0)
    h0 = TV(xs.a[..., 0:3, :, :], xs.b)
    h1 = TV(xs.a[..., 3:6, :, :], xs.b)
    t = fp6_inv(_norm(
        _rsub(fp6_mul(h0, h0), fp6_mul_xi(fp6_mul(h1, h1))), 2.0))
    r0 = fp6_mul(h0, t)
    r1 = _norm(tneg(fp6_mul(h1, t)), 2.0)
    return _as12(_tcat([r0, r1], -3))


def fp12_eq_one(x: TV):
    """x == 1 (canonical compare), collapsing all coefficient axes."""
    one = jnp.broadcast_to(jnp.asarray(_ONE12_M), x.a.shape)
    return jnp.all(tcanon(x) == tcanon(TV(one, 1.0)), axis=(-2, -1))


# Frobenius^2: w-power k picks up δ_k = ξ^(k(q^2-1)/6), which lands in
# Fq (checked below), so the whole map is ONE stacked mont_mul by a
# per-slot constant vector.

def _fq2_pow_int(c0: int, c1: int, e: int) -> Tuple[int, int]:
    r0, r1 = 1, 0
    b0, b1 = c0 % Q, c1 % Q
    while e:
        if e & 1:
            r0, r1 = (r0 * b0 - r1 * b1) % Q, (r0 * b1 + r1 * b0) % Q
        b0, b1 = (b0 * b0 - b1 * b1) % Q, (2 * b0 * b1) % Q
        e >>= 1
    return r0, r1


def _build_frob2() -> np.ndarray:
    rows = np.zeros((12, NLIMB), np.int32)
    for i in range(2):
        for j in range(3):
            k = i + 2 * j
            d0, d1 = _fq2_pow_int(1, 1, k * (Q * Q - 1) // 6)
            assert d1 == 0, "frobenius^2 delta not in Fq"
            s = 3 * i + j
            rows[2 * s] = rows[2 * s + 1] = _mont_l(d0)
    return rows


_FROB2_M = _build_frob2()


def fp12_frob2(x: TV) -> TV:
    return tmul(x, TV(jnp.asarray(_FROB2_M), 1.0))


# ------------------------------------------------------ G2 decompress
#
# Affine decompression on the twist E'(Fp2): y^2 = x^3 + 4(1+u). The
# Miller loop consumes affine (x, y), so no inversion is needed — the
# sqrt IS the whole cost, two fixed-exponent fp2 power loops batched
# over every point in the dispatch.

def g2_decompress(c1_std, c0_std, sign_big, is_inf, valid_in):
    """[..., 32] standard-domain x-coordinate limbs (c1/c0 halves,
    both < q enforced host-side) + flag vectors -> ((x, y) Montgomery
    fp2 TVs, valid[...]). Infinity rows carry garbage coordinates;
    callers mask with is_inf."""
    x_std = jnp.stack([c0_std, c1_std], axis=-2)
    x = TV(mont_mul(x_std, jnp.broadcast_to(jnp.asarray(_R2_L),
                                            x_std.shape)), 2.0)
    yy = fp2_add(fp2_mul(fp2_mul(x, x), x),
                 TV(jnp.broadcast_to(jnp.asarray(_B_TWIST_M),
                                     x.a.shape), 1.0))
    y, on_curve = fp2_sqrt(_norm(yy, 2.0))
    # sign: lexicographic (c1, c0) compare against (q-1)/2, matching
    # the byte-level convention of crypto.bls12_381.g2_compress
    yc = tcanon(y)
    y0_std = mont_mul(yc[..., 0, :],
                      jnp.broadcast_to(jnp.asarray(
                          _int_to_limbs(1)), yc[..., 0, :].shape))
    y1_std = mont_mul(yc[..., 1, :],
                      jnp.broadcast_to(jnp.asarray(
                          _int_to_limbs(1)), yc[..., 1, :].shape))
    y0c = _cond_sub(y0_std, _Q_L)
    y1c = _cond_sub(y1_std, _Q_L)
    c1_zero = jnp.all(y1c == 0, axis=-1)
    got_big = jnp.where(c1_zero, _geq(y0c, _HALF_P1_L),
                        _geq(y1c, _HALF_P1_L))
    flip = got_big != sign_big
    yn = _norm(fp2_neg(y), 2.0)
    y = TV(jnp.where(flip[..., None, None], yn.a, y.a), 2.0)
    valid = valid_in & (on_curve | is_inf)
    return x, y, valid


# --------------------------------------------- complete addition (RCB)
#
# One generic Renes-Costello-Batina complete-addition ladder rung,
# parameterized over the base field so G1 ([..., 32] Fq lanes) and G2
# on the twist ([..., 2, 32] fp2 lanes) share the formula. Each layer
# of independent products is ONE stacked multiply.

class _FqField:
    lane_axis = -2
    b3 = TV(jnp.asarray(_int_to_limbs(12 * R_MONT % Q)), 1.0)

    @staticmethod
    def mul_many(x, y):
        return tmul(x, y)

    @staticmethod
    def lane(p, k):
        return TV(p.a[..., k, :], p.b)


class _Fp2Field:
    lane_axis = -3
    b3 = TV(jnp.asarray(_B3_TWIST_M), 1.0)

    @staticmethod
    def mul_many(x, y):
        return fp2_mul_many(x, y)

    @staticmethod
    def lane(p, k):
        return TV(p.a[..., k, :, :], p.b)


def padd_rcb(P1, P2, field=_FqField):
    """Complete addition (RCB 2016 Alg. 7, a=0, b3=12·(1 or 1+u)) in
    three stacked-multiply layers: 6 + 2 + 6 lanes. P1/P2 are (X, Y,
    Z) TV triples in the field's layout; identity is (0, 1, 0)."""
    X1, Y1, Z1 = (_norm(c, 2.0) for c in P1)
    X2, Y2, Z2 = (_norm(c, 2.0) for c in P2)
    ax = field.lane_axis
    l1 = _tstack([X1, Y1, Z1, _radd(X1, Y1), _radd(Y1, Z1),
                  _radd(X1, Z1)], ax)
    r1 = _tstack([X2, Y2, Z2, _radd(X2, Y2), _radd(Y2, Z2),
                  _radd(X2, Z2)], ax)
    p = field.mul_many(l1, r1)
    t0, t1, t2, t3l, t4l, xl = (field.lane(p, k) for k in range(6))
    t3 = _rsub(t3l, _radd(t0, t1))               # X1Y2 + X2Y1
    t4 = _rsub(t4l, _radd(t1, t2))               # Y1Z2 + Y2Z1
    y3 = _rsub(xl, _radd(t0, t2))                # X1Z2 + X2Z1
    t0_3 = _radd(_radd(t0, t0), t0)              # 3·t0
    b3b = TV(jnp.broadcast_to(
        field.b3.a, _norm(t2, 2.0).a.shape), field.b3.b)
    p2 = field.mul_many(_tstack([_norm(t2, 2.0), _norm(y3, 2.0)], ax),
                        _tstack([b3b, b3b], ax))
    b3t2, y3m = field.lane(p2, 0), field.lane(p2, 1)
    z3 = _radd(t1, b3t2)
    t1m = _rsub(t1, b3t2)
    l3 = _tstack([_norm(t4, 2.0), _norm(t3, 2.0), _norm(y3m, 2.0),
                  _norm(t1m, 2.0), _norm(z3, 2.0), _norm(t0_3, 2.0)],
                 ax)
    r3 = _tstack([_norm(y3m, 2.0), _norm(t1m, 2.0), _norm(t0_3, 2.0),
                  _norm(z3, 2.0), _norm(t4, 2.0), _norm(t3, 2.0)], ax)
    q = field.mul_many(l3, r3)
    q0, q1, q2, q3, q4, q5 = (field.lane(q, k) for k in range(6))
    X3 = _norm(_rsub(q1, q0), 2.0)               # t3·t1m - t4·y3m
    Y3 = _norm(_radd(q2, q3), 2.0)               # y3m·t0_3 + t1m·z3
    Z3 = _norm(_radd(q4, q5), 2.0)               # z3·t4 + t0_3·t3
    return X3, Y3, Z3


def g2_identity(shape: Tuple[int, ...]):
    """Projective identity (0 : 1 : 0) on the twist, Montgomery fp2."""
    z = jnp.zeros(tuple(shape) + (2, NLIMB), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(_ONE2_M),
                           tuple(shape) + (2, NLIMB))
    return TV(z, 1.0), TV(one, 1.0), TV(z, 1.0)


# ------------------------------------------------- host byte plumbing

def _be48_to_limbs(body: np.ndarray) -> np.ndarray:
    """[N, 48] big-endian bytes (flags already masked) -> [N, 32]
    limbs; vectorized (3 bytes = 2 limbs), no Python bigints."""
    N = body.shape[0]
    le = body[:, ::-1].astype(np.int32)
    groups = le.reshape(N, 16, 3)
    v24 = groups[:, :, 0] + (groups[:, :, 1] << 8) \
        + (groups[:, :, 2] << 16)
    limbs = np.empty((N, NLIMB), dtype=np.int32)
    limbs[:, 0::2] = v24 & MASK
    limbs[:, 1::2] = v24 >> RADIX
    return limbs


def _limbs_lt_q(limbs: np.ndarray) -> np.ndarray:
    lt = np.zeros(limbs.shape[0], dtype=bool)
    decided = np.zeros(limbs.shape[0], dtype=bool)
    for i in range(NLIMB - 1, -1, -1):
        qi = int(_Q_L[i])
        lt |= (~decided) & (limbs[:, i] < qi)
        decided |= limbs[:, i] != qi
    return lt


def pack_g2_compressed(raws: np.ndarray):
    """[N, 96] uint8 big-endian compressed G2 -> (c1 limbs [N, 32],
    c0 limbs [N, 32], sign_big [N], is_inf [N], valid [N]). Mirrors
    `pack_compressed` for the 96-byte two-coordinate encoding: flags
    ride the first byte of the c1 half."""
    raws = np.asarray(raws, dtype=np.uint8)
    N = raws.shape[0]
    flags = raws[:, 0]
    compressed = (flags & 0x80) != 0
    is_inf = (flags & 0x40) != 0
    sign_big = (flags & 0x20) != 0
    b1 = raws[:, :48].copy()
    b1[:, 0] &= 0x1F
    c1 = _be48_to_limbs(b1)
    c0 = _be48_to_limbs(raws[:, 48:])
    inf_ok = is_inf & (flags == 0xC0) & ~np.any(raws[:, 1:], axis=1)
    valid = compressed & (inf_ok
                          | (~is_inf & _limbs_lt_q(c1)
                             & _limbs_lt_q(c0)))
    bad = ~valid | is_inf
    c1[bad] = 0
    c0[bad] = 0
    return c1, c0, sign_big & ~is_inf, is_inf & valid, valid
