"""Batched SHA3-256 in JAX — the MPT state engine's device hash path.

The trie (state/trie.py) hashes nodes with ``hashlib.sha3_256`` — NOT
the SHA-256 the merkle ledger uses (ops/sha256.py) — so the state
engine needs its own kernel. Same shape as the SHA-256 one: host-side
padding into fixed-shape word arrays, one compiled executable per
power-of-two block-count bucket, a ``lax.scan`` over the block axis
with per-message masking for ragged block counts.

Design notes (TPU-first):
 - Keccak-f[1600] runs on 64-bit lanes; the VPU is 32-bit, so every
   lane is an (hi, lo) uint32 pair and the 64-bit rotations decompose
   into static 32-bit shift/or pairs (rho offsets are compile-time
   constants, so each lane's rotation is two shifts and an or — no
   64-bit emulation arithmetic anywhere).
 - The 24 rounds run under ``lax.fori_loop`` with the round-constant
   table indexed in-loop; state lives as two [B, 25] uint32 arrays.
 - SHA3-256 rate is 136 bytes = 17 lanes; absorb XORs the padded block
   into lanes 0..16 and permutes. The digest is lanes 0..3 serialized
   little-endian (Keccak convention — the opposite endianness of the
   SHA-2 kernel's big-endian words).
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from plenum_tpu.observability import telemetry as _tmy
from plenum_tpu.ops import scatter_ragged_rows

RATE_BYTES = 136          # SHA3-256: r = 1088 bits
RATE_LANES = RATE_BYTES // 8

_RC = np.array([
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
], dtype=np.uint64)
_RC_HI = (_RC >> 32).astype(np.uint32)
_RC_LO = (_RC & 0xFFFFFFFF).astype(np.uint32)

# rho rotation offsets, indexed [x][y] for lane x + 5y
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]


# bit width of one lane half — structure of the uint32-pair emulation,
# named so the rotation arithmetic below reads as what it is
_HALF_BITS = 32


def _rotl64(hi, lo, n: int):
    """Rotate an (hi, lo) uint32 lane pair left by the STATIC amount n."""
    n &= 63
    if n == 0:
        return hi, lo
    if n == _HALF_BITS:
        return lo, hi
    if n < _HALF_BITS:
        m = jnp.uint32(n)
        c = jnp.uint32(_HALF_BITS - n)
        return (hi << m) | (lo >> c), (lo << m) | (hi >> c)
    m = jnp.uint32(n - _HALF_BITS)
    c = jnp.uint32(2 * _HALF_BITS - n)
    return (lo << m) | (hi >> c), (hi << m) | (lo >> c)


def _keccak_round(hi, lo, rc_hi, rc_lo):
    """One Keccak-f round over lane lists (25 arrays per half)."""
    c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20]
            for x in range(5)]
    c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20]
            for x in range(5)]
    for x in range(5):
        rh, rl = _rotl64(c_hi[(x + 1) % 5], c_lo[(x + 1) % 5], 1)
        d_hi = c_hi[(x - 1) % 5] ^ rh
        d_lo = c_lo[(x - 1) % 5] ^ rl
        for y in range(5):
            i = x + 5 * y
            hi[i] = hi[i] ^ d_hi
            lo[i] = lo[i] ^ d_lo
    # rho + pi
    b_hi: List = [None] * 25
    b_lo: List = [None] * 25
    for x in range(5):
        for y in range(5):
            j = y + 5 * ((2 * x + 3 * y) % 5)
            b_hi[j], b_lo[j] = _rotl64(hi[x + 5 * y], lo[x + 5 * y],
                                       _ROT[x][y])
    # chi
    out_hi = [None] * 25
    out_lo = [None] * 25
    for y in range(5):
        for x in range(5):
            i = x + 5 * y
            i1 = (x + 1) % 5 + 5 * y
            i2 = (x + 2) % 5 + 5 * y
            out_hi[i] = b_hi[i] ^ (~b_hi[i1] & b_hi[i2])
            out_lo[i] = b_lo[i] ^ (~b_lo[i1] & b_lo[i2])
    out_hi[0] = out_hi[0] ^ rc_hi
    out_lo[0] = out_lo[0] ^ rc_lo
    return out_hi, out_lo


def _keccak_f(state_hi, state_lo):
    """Keccak-f[1600] over [..., 25] uint32 half-lane arrays."""
    rc_hi = jnp.asarray(_RC_HI)
    rc_lo = jnp.asarray(_RC_LO)

    def round_fn(t, carry):
        sh, sl = carry
        hi = [sh[..., i] for i in range(25)]
        lo = [sl[..., i] for i in range(25)]
        hi, lo = _keccak_round(hi, lo, rc_hi[t], rc_lo[t])
        return jnp.stack(hi, axis=-1), jnp.stack(lo, axis=-1)

    return lax.fori_loop(0, 24, round_fn, (state_hi, state_lo))


@functools.partial(jax.jit, static_argnames=("nblocks",))
def _sha3_blocks(blocks, nvalid, nblocks: int):
    """blocks: [B, nblocks, 17, 2] u32 (lane lo at [..., 0], hi at
    [..., 1]); nvalid: [B] i32 → digests [B, 8] u32 in little-endian
    serialization order (l0.lo, l0.hi, l1.lo, …)."""
    b = blocks.shape[0]
    state_hi = jnp.zeros((b, 25), dtype=jnp.uint32)
    state_lo = jnp.zeros((b, 25), dtype=jnp.uint32)
    pad = ((0, 0), (0, 25 - RATE_LANES))

    def step(carry, xs):
        sh, sl = carry
        block, idx = xs
        nh = sh ^ jnp.pad(block[..., 1], pad)
        nl = sl ^ jnp.pad(block[..., 0], pad)
        nh, nl = _keccak_f(nh, nl)
        mask = (idx < nvalid)[..., None]
        return (jnp.where(mask, nh, sh), jnp.where(mask, nl, sl)), None

    idxs = jnp.arange(nblocks, dtype=jnp.int32)
    blocks_t = jnp.moveaxis(blocks, 1, 0)  # [nblocks, B, 17, 2]
    (state_hi, state_lo), _ = lax.scan(
        step, (state_hi, state_lo), (blocks_t, idxs))
    lanes = []
    for i in range(4):
        lanes.append(state_lo[..., i])
        lanes.append(state_hi[..., i])
    return jnp.stack(lanes, axis=-1)


def pad_sha3_messages(msgs: Sequence[bytes], nblocks: int = None
                      ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Keccak-pad `msgs` (domain suffix 0x06, final 0x80) into
    ([B, nblocks, 17, 2] u32 half-lane words, [B] i32 block counts)."""
    need = [len(m) // RATE_BYTES + 1 for m in msgs]
    maxb = max(need) if need else 1
    if nblocks is None:
        # bucket to power of two to bound recompiles
        nblocks = 1
        while nblocks < maxb:
            nblocks *= 2
    assert maxb <= nblocks
    n = len(msgs)
    width = nblocks * RATE_BYTES
    ln0 = len(msgs[0]) if msgs else 0
    uniform = bool(msgs) and all(len(m) == ln0 for m in msgs)
    if not msgs or uniform:
        out = np.zeros((n, width), dtype=np.uint8)
    if uniform:
        # uniform lengths (level batches of same-shape nodes): one
        # vectorized fill, no per-message loop
        if ln0:
            out[:, :ln0] = np.frombuffer(b"".join(msgs), dtype=np.uint8) \
                .reshape(n, ln0)
        out[:, ln0] = 0x06
        out[:, need[0] * RATE_BYTES - 1] ^= 0x80
    elif msgs:
        # mixed lengths: one flat vectorized scatter (shared core in
        # ops.scatter_ragged_rows, same as ops/sha256.pad_messages —
        # the per-message loop was the host bottleneck for large mixed
        # batches); only the Keccak domain/final markers differ
        out, lens = scatter_ragged_rows(msgs, width)
        flat = out.reshape(-1)
        rows = np.arange(n, dtype=np.int64)
        flat[rows * width + lens] = 0x06
        ends = np.asarray(need, dtype=np.int64) * RATE_BYTES
        last = rows * width + ends - 1
        flat[last] = flat[last] ^ 0x80  # may share the 0x06 byte
    words = out.reshape(n, nblocks, RATE_LANES, 2, 4).astype(np.uint32)
    # little-endian u32 halves: [..., 0] = lo word, [..., 1] = hi word
    words = (words[..., 0] | words[..., 1] << 8 | words[..., 2] << 16
             | words[..., 3] << 24)
    nvalid = np.asarray(need, dtype=np.int32)
    # block-lane accounting (mirror of sha256.pad_messages): absorbs
    # beyond a message's `need` blocks are wasted bucket lanes
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_SHA3, int(nvalid.sum()), n * nblocks,
        shape=(n, nblocks))
    return words, nvalid, nblocks


def digests_to_array(dig: np.ndarray) -> np.ndarray:
    """[B, 8] u32 little-endian digest words → [B, 32] u8 digest bytes."""
    arr = np.ascontiguousarray(np.asarray(dig).astype("<u4"))
    return arr.view(np.uint8).reshape(-1, 32)


def digests_to_bytes(dig: np.ndarray) -> List[bytes]:
    arr = digests_to_array(dig)
    return [arr[i].tobytes() for i in range(arr.shape[0])]


def sha3_256_many(msgs: Sequence[bytes]) -> List[bytes]:
    """Batched SHA3-256 over arbitrary same-or-mixed-length messages."""
    if not msgs:
        return []
    words, nvalid, nblocks = pad_sha3_messages(msgs)
    dig = _sha3_blocks(jnp.asarray(words), jnp.asarray(nvalid), nblocks)
    return digests_to_bytes(np.asarray(dig))
