from plenum_tpu.client.wallet import Wallet, WalletStorageHelper
from plenum_tpu.client.client import PoolClient

__all__ = ["Wallet", "WalletStorageHelper", "PoolClient"]
