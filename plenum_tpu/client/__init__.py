from plenum_tpu.client.wallet import Wallet, WalletStorageHelper
from plenum_tpu.client.client import PoolClient
from plenum_tpu.client.network_client import NetworkedPoolClient

__all__ = ["Wallet", "WalletStorageHelper", "PoolClient",
           "NetworkedPoolClient"]
