"""Pool client: signs, submits, and confirms requests against a pool.

The reference keeps only the Wallet in-tree (plenum/client/wallet.py)
and delegates the full client to the external SDK; this framework ships
the client too, because rung-2/3 testing and the ops scripts need one:

- submit to all nodes (or a subset), track REQACK / REQNACK / REJECT
- confirm a request once f+1 nodes return matching Reply results
  (Quorums.reply — the BFT read quorum on write acks)
- accept a state-proof-bearing read from ONE node when the proof's
  BLS multi-signature verifies against the pool's registered keys
  (reference read_request_handler.py:39-56 attaches the multi-sig
  precisely so clients don't need f+1 matching answers)
- timer-driven resubmission of unconfirmed requests

Transport-agnostic: `send_fn(node_name, msg_dict)` is injected — the
SimNetwork client channel in tests, the TCP client stack in deployment
(server side: plenum_tpu/server/networked_node.py clientstack).
Inbound replies are fed to `receive(node_name, msg)` as either
MessageBase objects or wire dicts.
"""
from __future__ import annotations

import json
import logging
from typing import Callable, Dict, List, Optional, Sequence

from plenum_tpu.common.constants import OP_FIELD_NAME
from plenum_tpu.common.messages.node_messages import (
    Reject, Reply, RequestAck, RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService
from plenum_tpu.client.wallet import Wallet

logger = logging.getLogger(__name__)

_CLIENT_MSG_CLASSES = {c.typename: c for c in
                       (Reply, RequestAck, RequestNack, Reject)}


class RequestStatus:
    """Per-request bookkeeping: who acked/nacked, which results arrived."""

    def __init__(self, req: Request):
        self.request = req
        self.acks: set = set()
        self.nacks: Dict[str, str] = {}
        self.rejects: Dict[str, str] = {}
        self.replies: Dict[str, dict] = {}   # node -> result
        self.confirmed_result: Optional[dict] = None
        self.failed: bool = False            # terminally nacked/rejected
        self.proven: bool = False            # accepted via state proof

    @property
    def key(self):
        return (self.request.identifier, self.request.reqId)


def _result_fingerprint(result: dict) -> str:
    """Node-agnostic identity of a Reply result for quorum matching."""
    return json.dumps(result, sort_keys=True, default=str)


class PoolClient:
    def __init__(self, wallet: Wallet, node_names: Sequence[str],
                 send_fn: Callable[[str, dict], None],
                 timer: TimerService = None,
                 resubmit_interval: float = 15.0,
                 bls_verifier=None,
                 bls_key_provider: Callable[[str], Optional[str]] = None,
                 proof_max_age: Optional[float] = None,
                 get_time: Callable[[], float] = None):
        """bls_verifier + bls_key_provider(node_name → BLS pk) enable
        single-node trust for proof-bearing reads; without them every
        read needs the f+1 matching-reply quorum.

        proof_max_age (seconds, against get_time — wall clock by
        default): reject single-node proofs whose multi-sig timestamp
        is older than this, EXCEPT for reads that explicitly ask for
        historical state (operation carries a timestamp). Without a
        window, one malicious node can answer a current-state read
        with a genuine-but-stale proof (e.g. an absence proof captured
        before the value was written)."""
        import time as _time
        self.wallet = wallet
        self.node_names = list(node_names)
        self._send = send_fn
        self.quorums = Quorums(len(self.node_names))
        self._bls_verifier = bls_verifier
        self._bls_keys = bls_key_provider
        self._proof_max_age = proof_max_age
        self._get_time = get_time or _time.time
        self._pending: Dict[tuple, RequestStatus] = {}
        self._completed: Dict[tuple, RequestStatus] = {}
        self._resubmitter = None
        if timer is not None and resubmit_interval > 0:
            self._resubmitter = RepeatingTimer(
                timer, resubmit_interval, self._resubmit_pending)

    # ---------------------------------------------------------- submit

    def submit(self, operation: dict, identifier: str = None,
               taa_acceptance: dict = None) -> Request:
        """Sign an operation with the wallet and send it to every node."""
        req = self.wallet.sign_op(operation, identifier=identifier,
                                  taa_acceptance=taa_acceptance)
        return self.submit_request(req)

    def submit_request(self, req: Request) -> Request:
        status = RequestStatus(req)
        self._pending[status.key] = status
        self._broadcast(req)
        return req

    def _broadcast(self, req: Request):
        for name in self.node_names:
            try:
                self._send(name, req.as_dict())
            except Exception:
                logger.warning("send to %s failed", name, exc_info=True)

    def _resubmit_pending(self):
        for status in list(self._pending.values()):
            self._broadcast(status.request)

    # --------------------------------------------------------- receive

    def receive(self, node_name: str, msg) -> None:
        """Feed one inbound client-stack message (object or wire dict)."""
        if isinstance(msg, dict):
            msg = self._from_wire(msg)
            if msg is None:
                return
        if isinstance(msg, Reply):
            self._on_reply(node_name, msg)
        elif isinstance(msg, RequestAck):
            self._on_status(node_name, msg, "acks")
        elif isinstance(msg, RequestNack):
            self._on_status(node_name, msg, "nacks")
        elif isinstance(msg, Reject):
            self._on_status(node_name, msg, "rejects")

    @staticmethod
    def _result_key(result: dict):
        """(identifier, reqId) from a Reply result — write results are
        committed txns (author/reqId under txn.metadata, txn_util
        format), read results carry them at top level."""
        try:
            from plenum_tpu.common.txn_util import get_from, get_req_id
            frm, rid = get_from(result), get_req_id(result)
            if frm is not None or rid is not None:
                return (frm, rid)
        except Exception:
            pass
        return (result.get("identifier"), result.get("reqId"))

    @staticmethod
    def _from_wire(d: dict):
        cls = _CLIENT_MSG_CLASSES.get(d.get(OP_FIELD_NAME))
        if cls is None:
            return None
        fields = {k: v for k, v in d.items() if k != OP_FIELD_NAME}
        try:
            return cls(**fields)
        except Exception:
            logger.warning("malformed client-stack message: %r", d)
            return None

    def _on_status(self, node_name: str, msg, bucket: str):
        key = (msg.identifier, msg.reqId)
        status = self._pending.get(key) or self._completed.get(key)
        if status is None:
            return
        if bucket == "acks":
            status.acks.add(node_name)
            return
        getattr(status, bucket)[node_name] = msg.reason
        # terminal failure: once n-f nodes nacked/rejected, fewer than
        # f+1 can ever produce matching Replies — stop resubmitting
        refused = set(status.nacks) | set(status.rejects)
        if (key in self._pending
                and self.quorums.strong.is_reached(len(refused))):
            status.failed = True
            self._completed[key] = self._pending.pop(key)

    def _on_reply(self, node_name: str, msg: Reply):
        result = msg.result or {}
        key = self._result_key(result)
        status = self._pending.get(key)
        if status is None:
            return
        status.replies[node_name] = result
        # a verified state proof makes THIS single reply trustworthy:
        # the multi-sig (n-f nodes) vouches for the root, the proof
        # nodes tie the value to the root — no reply quorum needed. The
        # proof is only trusted for the REQUEST's own question: a reply
        # whose dest/type differ from what we asked carries a possibly
        # valid proof of the wrong fact (single-node substitution). The
        # freshness window applies to current-state reads only — a read
        # that names a timestamp WANTS an old root
        if self._proof_answers_request(status.request, result):
            historical = (status.request.operation or {}).get(
                "timestamp") is not None
            max_age = None if historical else self._proof_max_age
            if self.verify_state_proof(result, max_age=max_age,
                                       now=self._get_time()):
                status.confirmed_result = result
                status.proven = True
                self._completed[key] = self._pending.pop(key)
                return
        by_fp: Dict[str, List[str]] = {}
        for node, res in status.replies.items():
            by_fp.setdefault(_result_fingerprint(res), []).append(node)
        for fp, nodes in by_fp.items():
            if self.quorums.reply.is_reached(len(nodes)):
                status.confirmed_result = status.replies[nodes[0]]
                self._completed[key] = self._pending.pop(key)
                break

    # ----------------------------------------------------- state proofs

    @staticmethod
    def _proof_answers_request(req: Request, result: dict) -> bool:
        """The proof path is only valid when the result claims to answer
        exactly the operation we asked: same read type, same dest.
        Writes and mismatched reads always go through the reply
        quorum — otherwise one malicious node could 'confirm' a pending
        request with a valid proof of some unrelated fact."""
        from plenum_tpu.common.constants import TARGET_NYM, TXN_TYPE
        op = req.operation or {}
        if op.get(TXN_TYPE) != "105":
            return False
        return (isinstance(result, dict)
                and result.get(TXN_TYPE) == "105"
                and result.get("dest") == op.get(TARGET_NYM))

    def verify_state_proof(self, result: dict,
                           max_age: Optional[float] = None,
                           now: Optional[float] = None) -> bool:
        """True iff `result` carries a state proof whose BLS multi-sig
        verifies against n-f registered pool keys AND whose proof nodes
        tie the claimed value to the signed root. Every check fails
        closed: a reply that can't be proven simply falls back to the
        reply quorum.

        max_age (seconds, with `now`) additionally rejects proofs whose
        multi-sig timestamp is older than the window — without it a
        single node can serve provably-stale state (a valid multi-sig
        over an OLD root, e.g. an absence proof predating a committed
        write). Leave it None for historical (timestamped) queries,
        where an old root is the point."""
        if not isinstance(result, dict):
            return False
        from plenum_tpu.common.constants import STATE_PROOF
        # 1. cheap shape checks first — no pairing work for a reply
        # that could never be proof-confirmed
        kv = self._expected_state_kv(result)
        if kv is None:
            return False
        state_key, state_value = kv
        return self.verify_proof_dict(result.get(STATE_PROOF), state_key,
                                      state_value, max_age=max_age,
                                      now=now)

    def verify_proof_dict(self, sp, key: bytes, value: Optional[bytes],
                          ledger_id: Optional[int] = None,
                          max_age: Optional[float] = None,
                          now: Optional[float] = None) -> bool:
        """End-to-end check of ONE `{root_hash, proof_nodes,
        multi_signature}` dict as produced by the server's
        make_state_proof / make_state_proof_batch: the BLS multi-sig
        must verify against n-f registered pool keys AND vouch for
        exactly the proof's root on `ledger_id` (domain by default),
        and the proof nodes must tie `value` (or its absence, value
        None) to that root. Every check fails closed; callers that
        need to KNOW which check failed (the gateway's signed-read
        cache, diagnostics) use ``check_proof_dict``."""
        return self.check_proof_dict(sp, key, value, ledger_id=ledger_id,
                                     max_age=max_age, now=now) is None

    def check_proof_dict(self, sp, key: bytes, value: Optional[bytes],
                         ledger_id: Optional[int] = None,
                         max_age: Optional[float] = None,
                         now: Optional[float] = None) -> Optional[str]:
        """``verify_proof_dict`` with an attributable verdict: None on
        success, else a message NAMING the first failed check — a root
        mismatch (the multi-sig vouches for a different root than the
        proof claims), proof-node corruption (undecodable trie data or
        nodes that do not tie the value to the root) and an invalid
        multi-signature are different operational facts: the first is
        a stale/substituted answer, the second a mangled proof, the
        third a forged (or mis-keyed) signature."""
        pre = self._check_proof_pre(sp, ledger_id, max_age, now)
        if isinstance(pre, str):
            return pre
        multi, keys = pre
        # 4. the aggregated signature itself (the expensive pairing)
        try:
            sig_ok = self._bls_verifier.verify_multi_sig(
                multi.signature, multi.value.as_single_value(), keys)
        except Exception as e:
            return "multi-sig invalid: aggregate verification " \
                   "raised (%s)" % e
        if not sig_ok:
            return "multi-sig invalid: aggregate signature does not " \
                   "verify against the registered keys"
        return self._check_proof_nodes(sp, key, value)

    def check_proof_dicts(self, checks,
                          ledger_id: Optional[int] = None,
                          max_age: Optional[float] = None,
                          now: Optional[float] = None) -> list:
        """``check_proof_dict`` over a batch of (sp, key, value)
        triples sharing one ledger/freshness context → verdict per
        item. The cheap structural checks run per proof; every
        surviving proof's aggregate pairing then goes through ONE
        ``verify_multi_sigs_batch`` call — a single device launch above
        Config.BLS_PAIRING_DEVICE_MIN (the signed-read seam this
        batches is the same check the gateway cache admits on)."""
        results = [None] * len(checks)
        pending = []
        for i, (sp, key, value) in enumerate(checks):
            pre = self._check_proof_pre(sp, ledger_id, max_age, now)
            if isinstance(pre, str):
                results[i] = pre
            else:
                pending.append((i, pre[0], pre[1]))
        if not pending:
            return results
        try:
            verdicts = self._bls_verifier.verify_multi_sigs_batch(
                [(m.signature, m.value.as_single_value(), keys)
                 for _, m, keys in pending])
        except Exception as e:
            msg = "multi-sig invalid: aggregate verification " \
                  "raised (%s)" % e
            for i, _, _ in pending:
                results[i] = msg
            return results
        for (i, _, _), ok in zip(pending, verdicts):
            if not ok:
                results[i] = "multi-sig invalid: aggregate signature " \
                             "does not verify against the registered keys"
            else:
                sp, key, value = checks[i]
                results[i] = self._check_proof_nodes(sp, key, value)
        return results

    def _check_proof_pre(self, sp, ledger_id, max_age, now):
        """Steps 1-3 of ``check_proof_dict`` (everything before the
        pairing): an error string, or (MultiSignature, keys) ready for
        the aggregate check."""
        if self._bls_verifier is None or self._bls_keys is None:
            return "no BLS verifier/keys configured"
        from plenum_tpu.common.constants import (
            DOMAIN_LEDGER_ID, MULTI_SIGNATURE, PROOF_NODES, ROOT_HASH)
        if ledger_id is None:
            ledger_id = DOMAIN_LEDGER_ID
        if not isinstance(sp, dict) or MULTI_SIGNATURE not in sp:
            return "malformed state proof: not a dict with a " \
                   "multi-signature"
        try:
            from plenum_tpu.crypto.bls import MultiSignature
            multi = MultiSignature.from_dict(sp[MULTI_SIGNATURE])
        except Exception as e:
            return "multi-sig invalid: unparseable multi-signature " \
                   "(%s)" % e
        # 2. the multi-sig must vouch for exactly the proof's root, on
        # the ledger this read serves, and recently enough
        if multi.value.state_root_hash != sp.get(ROOT_HASH):
            return "root mismatch: multi-signature vouches for root " \
                   "%r, proof claims %r" % (multi.value.state_root_hash,
                                            sp.get(ROOT_HASH))
        if multi.value.ledger_id != ledger_id:
            return "ledger mismatch: multi-signature covers ledger " \
                   "%r, read serves %r" % (multi.value.ledger_id,
                                           ledger_id)
        if max_age is not None:
            ts = multi.value.timestamp
            ref = now if now is not None else __import__("time").time()
            if not isinstance(ts, (int, float)) or ref - ts > max_age:
                return "stale proof: multi-signature timestamp %r " \
                       "outside the %.0fs freshness window" % (ts,
                                                               max_age)
        # 3. enough distinct, registered signers (n-f)
        participants = list(multi.participants)
        if len(set(participants)) != len(participants):
            return "multi-sig invalid: duplicate participants"
        if not self.quorums.bls_signatures.is_reached(len(participants)):
            return "multi-sig invalid: %d signers below the n-f " \
                   "quorum" % len(participants)
        keys = []
        for name in participants:
            # participant names are proof-controlled input: a provider
            # that raises on a stranger (dict lookup) must read as
            # "unregistered", not as a crash
            try:
                pk = self._bls_keys(name)
            except (KeyError, TypeError, AttributeError):
                pk = None
            if pk is None:
                return "multi-sig invalid: unregistered signer %r" % name
            keys.append(pk)
        return multi, keys

    @staticmethod
    def _check_proof_nodes(sp, key: bytes,
                           value: Optional[bytes]) -> Optional[str]:
        """Step 5 of ``check_proof_dict``: the proof nodes must tie
        `value` (or its absence) to the signed root."""
        from plenum_tpu.common.constants import PROOF_NODES, ROOT_HASH
        try:
            from plenum_tpu.common.serializers.base58 import b58decode
            from plenum_tpu.state.pruning_state import PruningState
            root = b58decode(sp[ROOT_HASH])
            nodes = PruningState.deserialize_proof(sp[PROOF_NODES])
        except Exception as e:
            return "proof-node corruption: undecodable proof data " \
                   "(%s)" % e
        try:
            proven = PruningState.verify_state_proof(
                root, key, value, nodes)
        except Exception as e:
            return "proof-node corruption: proof walk raised (%s)" % e
        if not proven:
            return "proof-node corruption: proof nodes do not tie " \
                   "the claimed value to the signed root"
        return None

    @staticmethod
    def _expected_state_kv(result: dict):
        """(state_key, expected_encoded_value|None) for a read result,
        or None when the result type has no state mapping. The encoding
        must match the write handler's byte-for-byte (GET_NYM:
        request_handlers.nym_to_state_key / encode_state_value)."""
        from plenum_tpu.common.constants import TXN_TYPE
        if result.get(TXN_TYPE) != "105":
            return None
        dest = result.get("dest")
        if not isinstance(dest, str) or not dest:
            return None
        from plenum_tpu.common.state_codec import (
            encode_state_value, nym_to_state_key)
        key = nym_to_state_key(dest)
        if result.get("data") is None:
            return key, None  # proof of absence
        return key, encode_state_value(result["data"], result.get("seqNo"),
                                       result.get("txnTime"))

    # ----------------------------------------------------------- query

    def status_of(self, req: Request) -> Optional[RequestStatus]:
        key = (req.identifier, req.reqId)
        return self._pending.get(key) or self._completed.get(key)

    def result_of(self, req: Request) -> Optional[dict]:
        status = self.status_of(req)
        return status.confirmed_result if status else None

    def is_confirmed(self, req: Request) -> bool:
        return self.result_of(req) is not None

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    def close(self):
        if self._resubmitter is not None:
            self._resubmitter.stop()
