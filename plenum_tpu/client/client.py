"""Pool client: signs, submits, and confirms requests against a pool.

The reference keeps only the Wallet in-tree (plenum/client/wallet.py)
and delegates the full client to the external SDK; this framework ships
the client too, because rung-2/3 testing and the ops scripts need one:

- submit to all nodes (or a subset), track REQACK / REQNACK / REJECT
- confirm a request once f+1 nodes return matching Reply results
  (Quorums.reply — the BFT read quorum on write acks)
- timer-driven resubmission of unconfirmed requests

Transport-agnostic: `send_fn(node_name, msg_dict)` is injected — the
SimNetwork client channel in tests, the TCP client stack in deployment
(server side: plenum_tpu/server/networked_node.py clientstack).
Inbound replies are fed to `receive(node_name, msg)` as either
MessageBase objects or wire dicts.
"""
from __future__ import annotations

import json
import logging
from typing import Callable, Dict, List, Optional, Sequence

from plenum_tpu.common.constants import OP_FIELD_NAME
from plenum_tpu.common.messages.node_messages import (
    Reject, Reply, RequestAck, RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService
from plenum_tpu.client.wallet import Wallet

logger = logging.getLogger(__name__)

_CLIENT_MSG_CLASSES = {c.typename: c for c in
                       (Reply, RequestAck, RequestNack, Reject)}


class RequestStatus:
    """Per-request bookkeeping: who acked/nacked, which results arrived."""

    def __init__(self, req: Request):
        self.request = req
        self.acks: set = set()
        self.nacks: Dict[str, str] = {}
        self.rejects: Dict[str, str] = {}
        self.replies: Dict[str, dict] = {}   # node -> result
        self.confirmed_result: Optional[dict] = None
        self.failed: bool = False            # terminally nacked/rejected

    @property
    def key(self):
        return (self.request.identifier, self.request.reqId)


def _result_fingerprint(result: dict) -> str:
    """Node-agnostic identity of a Reply result for quorum matching."""
    return json.dumps(result, sort_keys=True, default=str)


class PoolClient:
    def __init__(self, wallet: Wallet, node_names: Sequence[str],
                 send_fn: Callable[[str, dict], None],
                 timer: TimerService = None,
                 resubmit_interval: float = 15.0):
        self.wallet = wallet
        self.node_names = list(node_names)
        self._send = send_fn
        self.quorums = Quorums(len(self.node_names))
        self._pending: Dict[tuple, RequestStatus] = {}
        self._completed: Dict[tuple, RequestStatus] = {}
        self._resubmitter = None
        if timer is not None and resubmit_interval > 0:
            self._resubmitter = RepeatingTimer(
                timer, resubmit_interval, self._resubmit_pending)

    # ---------------------------------------------------------- submit

    def submit(self, operation: dict, identifier: str = None,
               taa_acceptance: dict = None) -> Request:
        """Sign an operation with the wallet and send it to every node."""
        req = self.wallet.sign_op(operation, identifier=identifier,
                                  taa_acceptance=taa_acceptance)
        return self.submit_request(req)

    def submit_request(self, req: Request) -> Request:
        status = RequestStatus(req)
        self._pending[status.key] = status
        self._broadcast(req)
        return req

    def _broadcast(self, req: Request):
        for name in self.node_names:
            try:
                self._send(name, req.as_dict())
            except Exception:
                logger.warning("send to %s failed", name, exc_info=True)

    def _resubmit_pending(self):
        for status in list(self._pending.values()):
            self._broadcast(status.request)

    # --------------------------------------------------------- receive

    def receive(self, node_name: str, msg) -> None:
        """Feed one inbound client-stack message (object or wire dict)."""
        if isinstance(msg, dict):
            msg = self._from_wire(msg)
            if msg is None:
                return
        if isinstance(msg, Reply):
            self._on_reply(node_name, msg)
        elif isinstance(msg, RequestAck):
            self._on_status(node_name, msg, "acks")
        elif isinstance(msg, RequestNack):
            self._on_status(node_name, msg, "nacks")
        elif isinstance(msg, Reject):
            self._on_status(node_name, msg, "rejects")

    @staticmethod
    def _result_key(result: dict):
        """(identifier, reqId) from a Reply result — write results are
        committed txns (author/reqId under txn.metadata, txn_util
        format), read results carry them at top level."""
        try:
            from plenum_tpu.common.txn_util import get_from, get_req_id
            frm, rid = get_from(result), get_req_id(result)
            if frm is not None or rid is not None:
                return (frm, rid)
        except Exception:
            pass
        return (result.get("identifier"), result.get("reqId"))

    @staticmethod
    def _from_wire(d: dict):
        cls = _CLIENT_MSG_CLASSES.get(d.get(OP_FIELD_NAME))
        if cls is None:
            return None
        fields = {k: v for k, v in d.items() if k != OP_FIELD_NAME}
        try:
            return cls(**fields)
        except Exception:
            logger.warning("malformed client-stack message: %r", d)
            return None

    def _on_status(self, node_name: str, msg, bucket: str):
        key = (msg.identifier, msg.reqId)
        status = self._pending.get(key) or self._completed.get(key)
        if status is None:
            return
        if bucket == "acks":
            status.acks.add(node_name)
            return
        getattr(status, bucket)[node_name] = msg.reason
        # terminal failure: once n-f nodes nacked/rejected, fewer than
        # f+1 can ever produce matching Replies — stop resubmitting
        refused = set(status.nacks) | set(status.rejects)
        if (key in self._pending
                and self.quorums.strong.is_reached(len(refused))):
            status.failed = True
            self._completed[key] = self._pending.pop(key)

    def _on_reply(self, node_name: str, msg: Reply):
        result = msg.result or {}
        key = self._result_key(result)
        status = self._pending.get(key)
        if status is None:
            return
        status.replies[node_name] = result
        by_fp: Dict[str, List[str]] = {}
        for node, res in status.replies.items():
            by_fp.setdefault(_result_fingerprint(res), []).append(node)
        for fp, nodes in by_fp.items():
            if self.quorums.reply.is_reached(len(nodes)):
                status.confirmed_result = status.replies[nodes[0]]
                self._completed[key] = self._pending.pop(key)
                break

    # ----------------------------------------------------------- query

    def status_of(self, req: Request) -> Optional[RequestStatus]:
        key = (req.identifier, req.reqId)
        return self._pending.get(key) or self._completed.get(key)

    def result_of(self, req: Request) -> Optional[dict]:
        status = self.status_of(req)
        return status.confirmed_result if status else None

    def is_confirmed(self, req: Request) -> bool:
        return self.result_of(req) is not None

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    def close(self):
        if self._resubmitter is not None:
            self._resubmitter.stop()
