"""Client-side identity: a wallet of DID signers that signs requests.

Reference parity: plenum/client/wallet.py:38 (Wallet — addIdentifier,
signMsg/signRequest/signOp, sign_using_multi_sig, aliases) and :294
(WalletStorageHelper — keyrings dir with restrictive permissions). The
reference encrypts wallets with libsodium SecretBox; here storage holds
raw seeds behind 0600 file permissions, with the encryption seam left to
the deployment (the signing path, not storage crypto, is this layer's
job).
"""
from __future__ import annotations

import json
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from plenum_tpu.common.request import Request
from plenum_tpu.common.serializers.base58 import b58decode, b58encode
from plenum_tpu.common.serializers.serialization import (
    serialize_msg_for_signing)
from plenum_tpu.crypto.signer import DidSigner, Signer, SimpleSigner


class _IdData:
    __slots__ = ("signer", "alias")

    def __init__(self, signer: Signer, alias: Optional[str]):
        self.signer = signer
        self.alias = alias


_last_req_id = 0


def _new_req_id() -> int:
    """Strictly increasing time-derived request id (two requests signed
    in the same microsecond must not share a (identifier, reqId) key)."""
    global _last_req_id
    rid = max(time.time_ns() // 1000, _last_req_id + 1)
    _last_req_id = rid
    return rid


class Wallet:
    """Holds signing identities; knows nothing about transport."""

    def __init__(self, name: str = "wallet"):
        self.name = name
        self._ids: "OrderedDict[str, _IdData]" = OrderedDict()
        self.default_id: Optional[str] = None

    # ------------------------------------------------------- identities

    def add_identifier(self, signer: Signer = None, seed: bytes = None,
                       alias: str = None, did: bool = True
                       ) -> Tuple[str, Signer]:
        """Add (or create) a signing identity; first one becomes default."""
        if signer is None:
            signer = DidSigner(seed=seed) if did else SimpleSigner(seed=seed)
        idr = signer.identifier
        self._ids[idr] = _IdData(signer, alias)
        if self.default_id is None:
            self.default_id = idr
        return idr, signer

    def update_signer(self, identifier: str, signer: Signer):
        if identifier not in self._ids:
            raise KeyError("unknown identifier {}".format(identifier))
        self._ids[identifier].signer = signer

    @property
    def identifiers(self) -> List[str]:
        return list(self._ids)

    def alias_of(self, identifier: str) -> Optional[str]:
        data = self._ids.get(identifier)
        return data.alias if data else None

    def id_by_alias(self, alias: str) -> str:
        for idr, data in self._ids.items():
            if data.alias == alias:
                return idr
        raise KeyError("unknown alias {}".format(alias))

    def required_idr(self, identifier: str = None, alias: str = None) -> str:
        if alias is not None:
            return self.id_by_alias(alias)
        idr = identifier or self.default_id
        if idr is None or idr not in self._ids:
            raise KeyError("no such identifier in wallet: {}".format(idr))
        return idr

    def get_verkey(self, identifier: str = None) -> str:
        return self._ids[self.required_idr(identifier)].signer.verkey

    def _signer(self, identifier: str = None) -> Signer:
        return self._ids[self.required_idr(identifier)].signer

    # ---------------------------------------------------------- signing

    def sign_msg(self, msg, identifier: str = None) -> str:
        """Sign a dict (canonical serialization) or bytes → b58 sig."""
        return self._signer(identifier).sign(msg)

    def sign_request(self, req: Request, identifier: str = None) -> Request:
        """Single-signature: sets req.identifier (if unset) + signature."""
        idr = self.required_idr(identifier or req.identifier)
        if req.identifier is None:
            req.identifier = idr
        elif req.identifier != idr:
            # the server verifies against req.identifier's key; signing
            # as anyone else yields a request that can never authenticate
            raise ValueError(
                "identifier {} does not match request author {}; use "
                "sign_using_multi_sig for extra signatures".format(
                    idr, req.identifier))
        if req.reqId is None:
            req.reqId = _new_req_id()
        payload = serialize_msg_for_signing(req.signingPayloadState(idr))
        req.signature = self._signer(idr).sign(payload)
        return req

    def sign_using_multi_sig(self, req: Request,
                             identifier: str = None) -> Request:
        """Append this identity's signature to req.signatures (the
        multi-sig authn path, server: CoreAuthNr._verify_items)."""
        idr = self.required_idr(identifier)
        if req.reqId is None:
            req.reqId = _new_req_id()
        payload = serialize_msg_for_signing(req.signingPayloadState(idr))
        if req.signatures is None:
            req.signatures = {}
        req.signatures[idr] = self._signer(idr).sign(payload)
        return req

    def sign_op(self, operation: Dict, identifier: str = None,
                taa_acceptance: Dict = None) -> Request:
        """Build + sign a fresh request around an operation dict."""
        req = Request(identifier=self.required_idr(identifier),
                      reqId=_new_req_id(), operation=operation,
                      taaAcceptance=taa_acceptance)
        return self.sign_request(req)


class WalletStorageHelper:
    """Saves/loads wallets under a keyrings dir with restrictive
    permissions (reference WalletStorageHelper: dmode=0o700, fmode=0o600)."""

    def __init__(self, base_dir: str, dmode: int = 0o700,
                 fmode: int = 0o600):
        self.base_dir = os.path.abspath(base_dir)
        self._dmode = dmode
        self._fmode = fmode
        os.makedirs(self.base_dir, mode=dmode, exist_ok=True)
        os.chmod(self.base_dir, dmode)

    def _path(self, name: str) -> str:
        fname = name + ".wallet"
        path = os.path.abspath(os.path.join(self.base_dir, fname))
        # refuse path escapes ("../../etc/passwd" as a wallet name)
        if os.path.dirname(path) != self.base_dir:
            raise ValueError("invalid wallet name {!r}".format(name))
        return path

    def save_wallet(self, wallet: Wallet) -> str:
        data = {
            "name": wallet.name,
            "default": wallet.default_id,
            "ids": [{
                "seed": b58encode(d.signer.seed),
                "alias": d.alias,
                "did": isinstance(d.signer, DidSigner),
            } for d in wallet._ids.values()],
        }
        path = self._path(wallet.name)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     self._fmode)
        with os.fdopen(fd, "w") as f:
            json.dump(data, f)
        os.chmod(path, self._fmode)
        return path

    def load_wallet(self, name: str) -> Wallet:
        with open(self._path(name)) as f:
            data = json.load(f)
        w = Wallet(data["name"])
        for entry in data["ids"]:
            seed = b58decode(entry["seed"])
            w.add_identifier(seed=seed, alias=entry.get("alias"),
                             did=entry.get("did", True))
        if data.get("default"):
            w.default_id = data["default"]
        return w
