"""Ready-to-wire networked pool client: PoolClient over real sockets.

The reference delegates socket clients to the external SDK; this
framework ships one so `PoolClient` is usable against a live pool with
no manual transport assembly (README quick start). It dials every
node's client listener with the anonymous-encrypted `ClientConnection`
(network/stack.py), reconnects dropped links with backoff, feeds
inbound Replies into `PoolClient.receive`, and drives resubmission off
a wall-clock QueueTimer — the client-side mirror of the node's
keep-in-touch loop.

Async, single event loop, same cooperative style as NetworkedNode:
call `await client.start()`, then `await client.pump()` periodically
(or `run_until_confirmed`).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional, Sequence, Tuple

from plenum_tpu.client.client import PoolClient
from plenum_tpu.client.wallet import Wallet
from plenum_tpu.network.crypto_channel import HandshakeError
from plenum_tpu.network.stack import HA, ClientConnection
from plenum_tpu.runtime.timer import QueueTimer

logger = logging.getLogger(__name__)


class NetworkedPoolClient:
    """PoolClient + one ClientConnection per node.

    node_addrs: name -> (HA, expected node verkey bytes or None).
    """

    RECONNECT_BACKOFF = 1.0

    def __init__(self, wallet: Wallet,
                 node_addrs: Dict[str, Tuple[HA, Optional[bytes]]],
                 timer: Optional[QueueTimer] = None,
                 resubmit_interval: float = 5.0):
        self.timer = timer or QueueTimer(get_current_time=time.time)
        self.node_addrs = dict(node_addrs)
        self._conns: Dict[str, ClientConnection] = {}
        self._next_dial: Dict[str, float] = {}
        self.pool = PoolClient(wallet, list(node_addrs), self._send,
                               timer=self.timer,
                               resubmit_interval=resubmit_interval)

    # ------------------------------------------------------------ wiring

    def _send(self, node_name: str, msg_dict: dict) -> None:
        conn = self._conns.get(node_name)
        if conn is None or conn.conn is None or not conn.conn.alive:
            # resubmission retries once the link is back
            logger.debug("client: %s not connected; dropping send",
                         node_name)
            return
        try:
            conn.send(msg_dict)
        except Exception:
            logger.info("client: send to %s failed; closing link",
                        node_name)
            conn.close()

    async def _dial(self, name: str) -> None:
        ha, verkey = self.node_addrs[name]
        conn = ClientConnection(ha, expected_verkey=verkey)
        try:
            await conn.connect()
        except (HandshakeError, ConnectionError, OSError,
                asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
            # same failure set the node stacks' dial paths tolerate: a
            # down listener, a rotated verkey, or an accept-then-close
            # must cost one backoff, not fail the whole client
            logger.debug("client: dial %s failed: %s", name, e)
            self._next_dial[name] = time.monotonic() + \
                self.RECONNECT_BACKOFF
            return
        self._conns[name] = conn

    async def start(self) -> None:
        await asyncio.gather(*(self._dial(n) for n in self.node_addrs))

    async def stop(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()

    # ------------------------------------------------------------- pump

    async def pump(self) -> None:
        """One cooperative tick: drain inbound replies, heal links,
        fire timers (resubmission)."""
        for name, conn in list(self._conns.items()):
            while conn.rx:
                self.pool.receive(name, conn.rx.popleft())
            if conn.conn is None or not conn.conn.alive:
                self._conns.pop(name, None)
        now = time.monotonic()
        for name in self.node_addrs:
            if name not in self._conns and \
                    now >= self._next_dial.get(name, 0.0):
                await self._dial(name)
        self.timer.service()

    async def run_until_confirmed(self, req, timeout: float = 30.0):
        """Pump until `req` is confirmed (f+1 matching Replies) or
        `timeout` elapses; returns the confirmed result dict."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            await self.pump()
            if self.pool.is_confirmed(req):
                return self.pool.result_of(req)
            await asyncio.sleep(0.01)
        raise TimeoutError("request {} unconfirmed after {}s".format(
            (req.identifier, req.reqId), timeout))

    # ------------------------------------------------------- convenience

    def submit(self, operation: dict, **kwargs):
        return self.pool.submit(operation, **kwargs)
