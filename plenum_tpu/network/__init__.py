from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import NodeStack, ClientStack, HA, RemoteInfo
