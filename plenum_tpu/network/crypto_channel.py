"""Authenticated encrypted channel — the CurveZMQ equivalent.

Reference role: stp_zmq/zstack.py uses CurveCP (libsodium) to give every
inter-node link confidentiality + mutual authentication against a
directory of allowed public keys. This module provides the same property
over any byte stream with a SIGMA-I-style handshake built from OpenSSL
primitives (`cryptography`: X25519 ECDH, Ed25519 identity signatures,
HKDF-SHA256, ChaCha20-Poly1305 AEAD):

  M1  I→R:  eph_i                                  (32B X25519 pub)
  M2  R→I:  eph_r || AEAD(kh_r, vk_r || sig_r(transcript))
  M3  I→R:  AEAD(kh_i, vk_i || sig_i(transcript))

where transcript = SHA256(M1 || eph_r), kh_* are handshake keys from
HKDF(DH(eph_i, eph_r)), and vk/sig are the party's static Ed25519 verkey
and its signature over the transcript (role-tagged). Signing-then-
encrypting hides identities from passive observers (SIGMA-I); binding
the static key to the ephemerals via signature gives mutual auth and
forward secrecy. Anonymous initiators (clients) send a zero verkey and
empty signature — accepted only by listeners configured to allow it
(client stack; request-level ed25519 signatures still authenticate every
write, reference plenum/server/client_authn.py).

Traffic protection: per-direction ChaCha20-Poly1305 keys with a 96-bit
counter nonce. Everything here is sans-IO: the stack moves the bytes.
"""
from __future__ import annotations

import hashlib
from typing import Optional, Tuple

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.exceptions import InvalidSignature
except ImportError:        # soft dep: pure-Python RFC-vetted fallback
    from plenum_tpu.crypto.pure_channel_crypto import (
        ChaCha20Poly1305, Ed25519PrivateKey, Ed25519PublicKey, HKDF,
        InvalidSignature, X25519PrivateKey, X25519PublicKey, hashes,
        serialization)

PROTO_MAGIC = b"PTX1"
ANON_VK = b"\x00" * 32

_RAW = serialization.Encoding.Raw
_RAW_PUB = serialization.PublicFormat.Raw
_RAW_PRIV = serialization.PrivateFormat.Raw
_NOENC = serialization.NoEncryption()


class HandshakeError(Exception):
    pass


def _pub_bytes(key) -> bytes:
    return key.public_key().public_bytes(_RAW, _RAW_PUB)


def _hkdf(secret: bytes, salt: bytes, info: bytes, n: int) -> bytes:
    return HKDF(algorithm=hashes.SHA256(), length=n, salt=salt,
                info=info).derive(secret)


class CipherState:
    """One direction of traffic: AEAD key + 96-bit counter nonce."""

    def __init__(self, key: bytes):
        self._aead = ChaCha20Poly1305(key)
        self._n = 0

    def _next_nonce(self) -> bytes:
        n = self._n
        self._n += 1
        return n.to_bytes(12, "big")

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        return self._aead.encrypt(self._next_nonce(), plaintext, aad)

    def decrypt(self, ciphertext: bytes, aad: bytes = b"") -> bytes:
        try:
            return self._aead.decrypt(self._next_nonce(), ciphertext, aad)
        except Exception as e:
            raise HandshakeError("decrypt failed: {}".format(e))


class Session:
    """Established channel: encrypt/decrypt application frames."""

    def __init__(self, send_key: bytes, recv_key: bytes,
                 peer_verkey: Optional[bytes]):
        self.tx = CipherState(send_key)
        self.rx = CipherState(recv_key)
        # peer's static ed25519 verkey (None = anonymous client)
        self.peer_verkey = peer_verkey if peer_verkey != ANON_VK else None

    def encrypt(self, data: bytes) -> bytes:
        return self.tx.encrypt(data)

    def decrypt(self, data: bytes) -> bytes:
        return self.rx.decrypt(data)


def _derive(dh: bytes, transcript: bytes):
    """→ (kh_i, kh_r, k_i2r, k_r2i): handshake + traffic keys."""
    okm = _hkdf(dh, transcript, b"ptx-keys", 32 * 4)
    return okm[0:32], okm[32:64], okm[64:96], okm[96:128]


class Initiator:
    """Client side of the handshake (the dialing party)."""

    def __init__(self, static_sk: Optional[Ed25519PrivateKey],
                 expected_peer_vk: Optional[bytes]):
        """static_sk None → anonymous. expected_peer_vk: the registry
        verkey the responder MUST prove (None = accept any, record it)."""
        self._static_sk = static_sk
        self._expected_vk = expected_peer_vk
        self._eph = X25519PrivateKey.generate()
        self._m1 = None
        self._keys = None
        self._transcript = None

    def message1(self) -> bytes:
        self._m1 = PROTO_MAGIC + _pub_bytes(self._eph)
        return self._m1

    def consume_message2(self, m2: bytes) -> bytes:
        """Verify the responder, → message3 bytes."""
        if len(m2) < 32:
            raise HandshakeError("short handshake message2")
        eph_r = m2[:32]
        ct = m2[32:]
        dh = self._eph.exchange(X25519PublicKey.from_public_bytes(eph_r))
        transcript = hashlib.sha256(self._m1 + eph_r).digest()
        kh_i, kh_r, k_i2r, k_r2i = _derive(dh, transcript)
        payload = CipherState(kh_r).decrypt(ct)
        vk_r, sig_r = payload[:32], payload[32:]
        try:
            Ed25519PublicKey.from_public_bytes(vk_r).verify(
                sig_r, b"resp" + transcript)
        except InvalidSignature:
            raise HandshakeError("responder signature invalid")
        if self._expected_vk is not None and vk_r != self._expected_vk:
            raise HandshakeError("responder key mismatch")
        self._keys = (k_i2r, k_r2i)
        self._transcript = transcript
        self.peer_verkey = vk_r
        if self._static_sk is None:
            payload3 = ANON_VK
        else:
            vk_i = _pub_bytes(self._static_sk)
            sig_i = self._static_sk.sign(b"init" + transcript)
            payload3 = vk_i + sig_i
        return CipherState(kh_i).encrypt(payload3)

    def session(self) -> Session:
        k_i2r, k_r2i = self._keys
        return Session(send_key=k_i2r, recv_key=k_r2i,
                       peer_verkey=self.peer_verkey)


class Responder:
    """Listener side of the handshake."""

    def __init__(self, static_sk: Ed25519PrivateKey,
                 allowed_vks=None, allow_anonymous: bool = False):
        """allowed_vks: callable(vk_bytes) -> bool, or a set of raw
        verkeys, or None = allow any authenticated peer."""
        self._static_sk = static_sk
        self._allowed = allowed_vks
        self._allow_anon = allow_anonymous
        self._eph = X25519PrivateKey.generate()
        self._kh_i = None
        self._keys = None
        self._transcript = None
        self.peer_verkey = None

    def consume_message1(self, m1: bytes) -> bytes:
        """→ message2 bytes."""
        if len(m1) != 36 or m1[:4] != PROTO_MAGIC:
            raise HandshakeError("bad handshake message1")
        eph_i = m1[4:]
        eph_r = _pub_bytes(self._eph)
        dh = self._eph.exchange(X25519PublicKey.from_public_bytes(eph_i))
        transcript = hashlib.sha256(m1 + eph_r).digest()
        kh_i, kh_r, k_i2r, k_r2i = _derive(dh, transcript)
        self._kh_i = kh_i
        self._keys = (k_i2r, k_r2i)
        self._transcript = transcript
        vk_r = _pub_bytes(self._static_sk)
        sig_r = self._static_sk.sign(b"resp" + transcript)
        return eph_r + CipherState(kh_r).encrypt(vk_r + sig_r)

    def consume_message3(self, m3: bytes) -> None:
        payload = CipherState(self._kh_i).decrypt(m3)
        vk_i = payload[:32]
        if vk_i == ANON_VK:
            if not self._allow_anon:
                raise HandshakeError("anonymous peers not allowed")
            self.peer_verkey = ANON_VK
            return
        sig_i = payload[32:]
        try:
            Ed25519PublicKey.from_public_bytes(vk_i).verify(
                sig_i, b"init" + self._transcript)
        except InvalidSignature:
            raise HandshakeError("initiator signature invalid")
        if self._allowed is not None:
            ok = (self._allowed(vk_i) if callable(self._allowed)
                  else vk_i in self._allowed)
            if not ok:
                raise HandshakeError("initiator key not in allow-list")
        self.peer_verkey = vk_i

    def session(self) -> Session:
        k_i2r, k_r2i = self._keys
        return Session(send_key=k_r2i, recv_key=k_i2r,
                       peer_verkey=self.peer_verkey)
