"""Node transport identity keys.

Reference: stp_zmq/zstack.py:183 initLocalKeys — each node has an ed25519
signing keypair whose seed also derives its Curve25519 transport keys,
stored in per-node key directories with public-key allow-lists. Here one
32-byte seed yields the Ed25519 identity used BOTH for message/batch
signing and for transport handshake authentication (crypto_channel);
on-disk layout is a key dir with `<name>.seed` (private) and
`verkeys/<peer>.key` (the allow-list / registry pins).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey)
    from cryptography.hazmat.primitives import serialization
except ImportError:        # soft dep: pure-Python RFC-vetted fallback
    from plenum_tpu.crypto.pure_channel_crypto import (
        Ed25519PrivateKey, serialization)

from plenum_tpu.common.serializers.base58 import b58decode, b58encode

_RAW = serialization.Encoding.Raw
_RAW_PUB = serialization.PublicFormat.Raw


class NodeKeys:
    """In-memory transport identity: ed25519 keypair from a 32-byte seed."""

    def __init__(self, seed: Optional[bytes] = None):
        self.seed = seed or os.urandom(32)
        if len(self.seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.sk = Ed25519PrivateKey.from_private_bytes(self.seed)
        self.verkey_raw = self.sk.public_key().public_bytes(_RAW, _RAW_PUB)
        self.verkey = b58encode(self.verkey_raw)

    def sign(self, data: bytes) -> bytes:
        return self.sk.sign(data)

    # ------------------------------------------------------------- disk

    @classmethod
    def init_local_keys(cls, key_dir: str, name: str,
                        seed: Optional[bytes] = None) -> "NodeKeys":
        """Create (or overwrite) this node's key files; → keys."""
        keys = cls(seed)
        os.makedirs(os.path.join(key_dir, "verkeys"), exist_ok=True)
        priv = os.path.join(key_dir, name + ".seed")
        with open(priv, "wb") as f:
            f.write(keys.seed)
        os.chmod(priv, 0o600)
        cls.save_verkey(key_dir, name, keys.verkey)
        return keys

    @classmethod
    def load_local_keys(cls, key_dir: str, name: str) -> "NodeKeys":
        with open(os.path.join(key_dir, name + ".seed"), "rb") as f:
            return cls(f.read())

    @staticmethod
    def save_verkey(key_dir: str, name: str, verkey_b58: str):
        """Pin a peer's verkey into the allow-list directory."""
        os.makedirs(os.path.join(key_dir, "verkeys"), exist_ok=True)
        with open(os.path.join(key_dir, "verkeys", name + ".key"), "w") as f:
            f.write(verkey_b58)

    @staticmethod
    def load_verkeys(key_dir: str) -> Dict[str, bytes]:
        """→ {peer_name: raw_verkey} from the allow-list directory."""
        vdir = os.path.join(key_dir, "verkeys")
        out = {}
        if os.path.isdir(vdir):
            for fn in os.listdir(vdir):
                if fn.endswith(".key"):
                    with open(os.path.join(vdir, fn)) as f:
                        out[fn[:-4]] = b58decode(f.read().strip())
        return out
