"""Authenticated TCP mesh transport — the ZStack equivalent.

Reference: stp_zmq/zstack.py:52 (ZStack: ROUTER listener + per-remote
DEALER sockets, CurveCP, ping/pong :750-794, quota-bounded service
:481-605, 128KB limit), kit_zstack.py:28 (KITZStack registry-driven
reconnects), plenum/common/batched.py:20,91 (per-remote outbox
coalescing into signed Batch messages), plenum/common/stacks.py:30,167
(NodeZStack / ClientZStack with client connection limits).

Design (TPU-native build): asyncio TCP instead of libzmq. Each node runs
one listener; for every registry peer it also dials an outgoing
connection (the "DEALER"): application data is sent ONLY on the dialed
connection, received ONLY on accepted ones — same directionality as the
reference's DEALER→ROUTER flow, so either side can restart and the
dialer's keep-in-touch loop re-establishes the link. Every connection is
encrypted+authenticated by the SIGMA handshake in crypto_channel (the
CurveZMQ stand-in); node listeners only accept registry verkeys, the
client listener accepts anonymous initiators (request signatures still
authenticate writes). Wire frames are 4-byte length-prefixed msgpack;
outboxes coalesce per tick into Ed25519-signed BATCH envelopes; receive
side is quota-bounded per service() call (backpressure for the
single-threaded prod loop).

Deliberately superseded reference components (not missing):

- ``ClientMessageProvider`` (stp_zmq/client_message_provider.py:14), the
  bounded retry deque for replies to disconnected clients. Client ids
  here are per-connection, so a queued reply could never be re-routed to
  a reconnect; instead the node re-serves committed Replies from its
  payload-digest index when the client re-sends the request
  (server/node.py `_committed_reply`) — the reference's own durable
  recovery path, minus the lossy in-memory queue in front of it.
- ``PortDispenser`` (stp_core/network/port_dispenser.py:11), the
  file-locked port allocator for parallel test runs. Rung-3 tests bind
  OS-assigned ports (``HA("127.0.0.1", 0)``, tests/test_network_stack.py)
  and read the bound port back, which cannot collide by construction.
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Set

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import OP_FIELD_NAME
from plenum_tpu.common.serializers.base58 import b58decode, b58encode
from plenum_tpu.common.serializers.serializers import MsgPackSerializer
from plenum_tpu.network.crypto_channel import (
    HandshakeError, Initiator, Responder, Session)
from plenum_tpu.network.keys import NodeKeys

logger = logging.getLogger(__name__)

from plenum_tpu.utils.metrics import MetricsName as _MN
_ENC_TIME = _MN.WIRE_ENCODE_TIME
_BYTES_SENT = _MN.TRANSPORT_BYTES_SENT

serializer = MsgPackSerializer()

PING_OP = "ping_"
PONG_OP = "pong_"
BATCH_OP = "BATCH"


def pack_message_groups(msgs, budget, msg_len_limit, who=""):
    """Shared size-budgeted packing for outbox flushes (node batches
    and client-reply coalescing use the SAME rules): yields
    ('raw', msg) for messages that must travel alone and
    ('group', [msgs]) for batchable runs under `budget`. A single
    message past `msg_len_limit` is dropped loudly — sending it would
    make the peer's read_frame limit check kill the connection.
    Each grouped message also costs a msgpack bin header (<=5 bytes);
    at thousands of small messages per batch that per-item overhead
    alone can push the sealed frame past the limit, so it is part of
    the size accounting."""
    PER_MSG = 8
    group, group_size = [], 0
    for m in msgs:
        if len(m) > msg_len_limit:
            logger.error(
                "%s: message of %d bytes exceeds the %d-byte frame "
                "limit - dropped (%r...)", who, len(m), msg_len_limit,
                m[:128])
            continue
        if len(m) + PER_MSG > budget:
            # too big to share an envelope, fine as its own raw frame
            if group:
                yield ('group', group)
                group, group_size = [], 0
            yield ('raw', m)
            continue
        if group and group_size + len(m) + PER_MSG > budget:
            yield ('group', group)
            group, group_size = [], 0
        group.append(m)
        group_size += len(m) + PER_MSG
    if group:
        yield ('group', group)


class HA(NamedTuple):
    host: str
    port: int


class RemoteInfo(NamedTuple):
    name: str
    ha: HA
    verkey: bytes  # raw 32-byte ed25519 verkey


class Connection:
    """One established (handshaken) stream + its read loop."""

    def __init__(self, reader, writer, session: Session, label: str):
        self.reader = reader
        self.writer = writer
        self.session = session
        self.label = label
        self.last_seen = time.monotonic()
        self.alive = True
        self.bytes_in = 0
        self.bytes_out = 0

    def send_frame(self, payload: bytes):
        data = self.session.encrypt(payload)
        self.writer.write(len(data).to_bytes(4, "big") + data)
        self.bytes_out += len(data) + 4

    async def read_frame(self, limit: int) -> Optional[bytes]:
        try:
            hdr = await self.reader.readexactly(4)
            n = int.from_bytes(hdr, "big")
            if n > limit + 64:  # AEAD tag + slack
                raise HandshakeError("oversized frame {}".format(n))
            data = await self.reader.readexactly(n)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return None
        self.bytes_in += n + 4
        self.last_seen = time.monotonic()
        return self.session.decrypt(data)

    def close(self):
        self.alive = False
        try:
            self.writer.close()
        except Exception:
            pass


async def _handshake_frames(reader, writer, step_in: bool, payload=None,
                            timeout: float = 10.0):
    """Length-prefixed plaintext handshake frame IO."""
    if payload is not None:
        writer.write(len(payload).to_bytes(4, "big") + payload)
        await writer.drain()
    if step_in:
        hdr = await asyncio.wait_for(reader.readexactly(4), timeout)
        n = int.from_bytes(hdr, "big")
        if n > 4096:
            raise HandshakeError("oversized handshake frame")
        return await asyncio.wait_for(reader.readexactly(n), timeout)
    return None


class Remote:
    """Peer handle: registry entry + outgoing connection + outbox
    (reference stp_zmq/remote.py)."""

    def __init__(self, info: RemoteInfo):
        self.info = info
        self.conn: Optional[Connection] = None
        self.outbox: deque = deque()
        self.connecting = False
        self.next_retry = 0.0
        self.retry_count = 0
        self.ping_sent_at = 0.0

    @property
    def name(self):
        return self.info.name

    @property
    def is_connected(self) -> bool:
        return self.conn is not None and self.conn.alive

    def disconnect(self):
        if self.conn is not None:
            self.conn.close()
            self.conn = None


class StackBase:
    """Shared listener + rx-queue machinery."""

    def __init__(self, name: str, ha: HA, keys: NodeKeys,
                 config: Optional[Config] = None):
        self.name = name
        self.ha = ha
        self.keys = keys
        self.config = config or Config()
        self._server: Optional[asyncio.AbstractServer] = None
        # decoded inbound messages: (msg_dict, frm_name)
        self.rx: deque = deque()
        self._tasks: Set[asyncio.Task] = set()
        self._stopped = False
        self.msg_len_limit = self.config.MSG_LEN_LIMIT
        from plenum_tpu.utils.metrics import NullMetricsCollector
        self.metrics = NullMetricsCollector()  # host node injects
        # interception seam for fault-injection tooling
        # (testing/adversary): on_send(msg, dst) / on_incoming(msg, frm)
        # may rewrite, duplicate, or drop wire traffic; None =
        # pass-through. The stack itself carries no fault behavior.
        self.wire_tap = None

    # ------------------------------------------------------------ server

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_accept, self.ha.host, self.ha.port)
        if self.ha.port == 0:  # ephemeral: record the real port
            self.ha = HA(self.ha.host,
                         self._server.sockets[0].getsockname()[1])
        logger.info("%s listening on %s:%d", self.name, *self.ha)

    async def stop(self):
        self._stopped = True
        if self._server is not None:
            self._server.close()
            self._server = None
        for t in list(self._tasks):
            t.cancel()
        self._tasks.clear()
        # server.close() does NOT cancel established connection handlers
        # — a "stopped" stack whose read loops keep answering heartbeats
        # is a zombie peers never detect as dead
        self._close_connections()

    def _close_connections(self):
        """Subclasses close every live connection they hold."""

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_event_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _on_accept(self, reader, writer):
        raise NotImplementedError

    # --------------------------------------------------------- rx path

    def _enqueue_wire(self, payload: bytes, frm: str):
        """Decode one wire frame (possibly a BATCH) into rx entries."""
        try:
            msg = serializer.deserialize(payload)
        except Exception:
            logger.warning("%s: undecodable frame from %s", self.name, frm)
            return
        if not isinstance(msg, dict):
            logger.warning("%s: non-dict frame from %s", self.name, frm)
            return
        self.rx.append((msg, frm))

    def service(self, on_message: Callable[[dict, str], None],
                quota: Optional[int] = None,
                size_quota: Optional[int] = None) -> int:
        """Drain up to quota inbound messages (reference zstack.py:481
        quota-bounded service)."""
        count = 0
        size = 0
        quota = quota if quota is not None else len(self.rx)
        while self.rx and count < quota:
            msg, frm = self.rx.popleft()
            count += 1
            size += len(str(msg))
            if self.wire_tap is not None:
                routed = self.wire_tap.on_incoming(msg, frm)
                if routed is not None:
                    for m, f in routed:
                        try:
                            on_message(m, f)
                        except Exception:
                            logger.exception(
                                "%s: handler failed for msg from %s",
                                self.name, f)
                    continue
            try:
                on_message(msg, frm)
            except Exception:
                logger.exception("%s: handler failed for msg from %s",
                                 self.name, frm)
            if size_quota is not None and size >= size_quota:
                break
        if count:
            self.metrics.add_event(_MN.TRANSPORT_MSGS_RECV, count)
        return count


class NodeStack(StackBase):
    """Inter-validator mesh: KIT reconnects + signed batching + liveness."""

    def __init__(self, name: str, ha: HA, keys: NodeKeys,
                 registry: Dict[str, RemoteInfo],
                 config: Optional[Config] = None,
                 on_connections_changed: Callable[[Set[str]], None] = None):
        super().__init__(name, ha, keys, config)
        self.remotes: Dict[str, Remote] = {}
        self._vk_to_name: Dict[bytes, str] = {}
        self._incoming: Dict[str, Connection] = {}
        self._on_conns_changed = on_connections_changed or (lambda s: None)
        self._last_connecteds: Set[str] = set()
        for info in registry.values():
            if info.name != self.name:
                self.add_remote(info)

    def _close_connections(self):
        for conn in list(self._incoming.values()):
            conn.close()
        self._incoming.clear()
        for remote in self.remotes.values():
            remote.disconnect()

    # ------------------------------------------------------- membership

    def add_remote(self, info: RemoteInfo):
        self.remotes[info.name] = Remote(info)
        self._vk_to_name[info.verkey] = info.name

    def remove_remote(self, name: str):
        remote = self.remotes.pop(name, None)
        if remote is not None:
            self._vk_to_name.pop(remote.info.verkey, None)
            remote.disconnect()
        conn = self._incoming.pop(name, None)
        if conn is not None:
            conn.close()
        self._emit_connecteds()

    def update_remote(self, info: RemoteInfo):
        """HA or key change from a pool NODE txn → reconnect."""
        old = self.remotes.get(info.name)
        if old is not None and old.info == info:
            return
        self.remove_remote(info.name)
        self.add_remote(info)

    @property
    def connecteds(self) -> Set[str]:
        return {n for n, r in self.remotes.items() if r.is_connected}

    def _emit_connecteds(self):
        conns = self.connecteds
        if conns != self._last_connecteds:
            self._last_connecteds = set(conns)
            self._on_conns_changed(conns)

    # -------------------------------------------------------- listener

    async def _on_accept(self, reader, writer):
        try:
            responder = Responder(self.keys.sk,
                                  allowed_vks=set(self._vk_to_name),
                                  allow_anonymous=False)
            m1 = await _handshake_frames(reader, writer, True)
            m2 = responder.consume_message1(m1)
            m3 = await _handshake_frames(reader, writer, True, payload=m2)
            responder.consume_message3(m3)
        except (HandshakeError, asyncio.TimeoutError, ConnectionError,
                OSError, asyncio.IncompleteReadError) as e:
            logger.info("%s: inbound handshake failed: %s", self.name, e)
            writer.close()
            return
        frm = self._vk_to_name[responder.peer_verkey]
        conn = Connection(reader, writer, responder.session(),
                          "{}<-{}".format(self.name, frm))
        old = self._incoming.get(frm)
        if old is not None:
            old.close()
        self._incoming[frm] = conn
        try:
            await self._read_loop(conn, frm)
        except (HandshakeError, ConnectionError, OSError,
                asyncio.IncompleteReadError) as e:
            # a bad frame (oversize, corrupt AEAD) must drop THIS link,
            # not surface as an unhandled asyncio exception
            logger.warning("%s: read from %s failed: %s",
                           self.name, frm, e)
            conn.close()
            if self._incoming.get(frm) is conn:
                del self._incoming[frm]

    async def _read_loop(self, conn: Connection, frm: str):
        while conn.alive:
            payload = await conn.read_frame(self.msg_len_limit)
            if payload is None:
                conn.close()
                break
            self._dispatch_frame(payload, frm, conn)
        if self._incoming.get(frm) is conn:
            del self._incoming[frm]

    def _dispatch_frame(self, payload: bytes, frm: str, conn: Connection):
        if payload == b"pi":
            # liveness probe: answer on the same (incoming) stream
            try:
                conn.send_frame(b"po")
            except Exception:
                conn.close()
            return
        if payload == b"po":
            remote = self.remotes.get(frm)
            if remote is not None:
                remote.ping_sent_at = 0.0
            return
        self._unpack_wire(payload, frm)

    def _unpack_wire(self, payload: bytes, frm: str):
        self.metrics.add_event(_MN.TRANSPORT_BYTES_RECV, len(payload))
        with self.metrics.measure_time(_MN.WIRE_DECODE_TIME):
            return self._unpack_wire_inner(payload, frm)

    def _unpack_wire_inner(self, payload: bytes, frm: str):
        try:
            msg = serializer.deserialize(payload)
        except Exception:
            logger.warning("%s: undecodable frame from %s", self.name, frm)
            return
        if not isinstance(msg, dict):
            return
        if msg.get(OP_FIELD_NAME) == BATCH_OP:
            if not self._verify_batch_sig(msg, frm):
                logger.warning("%s: bad batch signature from %s",
                               self.name, frm)
                return
            for raw in msg.get("messages", []):
                self._enqueue_wire(raw if isinstance(raw, bytes)
                                   else bytes(raw), frm)
            return
        self.rx.append((msg, frm))

    def _verify_batch_sig(self, batch: dict, frm: str) -> bool:
        remote = self.remotes.get(frm)
        if remote is None:
            return False
        sig = batch.get("signature")
        if not sig:
            return False
        from plenum_tpu.network.crypto_channel import (
            Ed25519PublicKey, InvalidSignature)
        content = b"".join(bytes(m) for m in batch.get("messages", []))
        try:
            Ed25519PublicKey.from_public_bytes(
                remote.info.verkey).verify(b58decode(sig), content)
            return True
        except (InvalidSignature, ValueError):
            return False

    # ---------------------------------------------------- KIT lifecycle

    def service_lifecycle(self):
        """Reconnects + heartbeats; call every prod tick (reference
        keep_in_touch.py:36 serviceLifecycle)."""
        if self._stopped:
            # a prod after stop() must not re-dial peers and resurrect
            # the zombie stop() just killed
            return
        now = time.monotonic()
        for remote in self.remotes.values():
            if remote.is_connected:
                self._maybe_ping(remote, now)
            elif not remote.connecting and now >= remote.next_retry:
                remote.connecting = True
                self._spawn(self._connect(remote))
        self._emit_connecteds()

    def _maybe_ping(self, remote: Remote, now: float):
        if not self.config.ENABLE_HEARTBEATS:
            return
        conn = remote.conn
        idle = now - conn.last_seen
        if remote.ping_sent_at and \
                now - remote.ping_sent_at > 2 * self.config.HEARTBEAT_FREQ:
            logger.info("%s: %s unresponsive, dropping link",
                        self.name, remote.name)
            remote.disconnect()
            remote.ping_sent_at = 0.0
            return
        if idle > self.config.HEARTBEAT_FREQ and not remote.ping_sent_at:
            try:
                conn.send_frame(b"pi")
                remote.ping_sent_at = now
            except Exception:
                remote.disconnect()

    async def _connect(self, remote: Remote):
        try:
            reader, writer = await asyncio.open_connection(
                remote.info.ha.host, remote.info.ha.port)
            initiator = Initiator(self.keys.sk,
                                  expected_peer_vk=remote.info.verkey)
            m2 = await _handshake_frames(reader, writer, True,
                                         payload=initiator.message1())
            m3 = initiator.consume_message2(m2)
            await _handshake_frames(reader, writer, False, payload=m3)
            conn = Connection(reader, writer, initiator.session(),
                              "{}->{}".format(self.name, remote.name))
            remote.conn = conn
            remote.retry_count = 0
            remote.ping_sent_at = 0.0
            self._spawn(self._outgoing_read_loop(remote, conn))
            logger.info("%s connected to %s", self.name, remote.name)
            self._emit_connecteds()
        except (HandshakeError, asyncio.TimeoutError, ConnectionError,
                OSError, asyncio.IncompleteReadError) as e:
            logger.debug("%s: connect to %s failed: %s",
                         self.name, remote.name, e)
            remote.retry_count += 1
            backoff = min(self.config.RETRY_TIMEOUT_NOT_RESTRICTED,
                          0.1 * (2 ** min(remote.retry_count, 6)))
            remote.next_retry = time.monotonic() + backoff
        finally:
            remote.connecting = False

    async def _outgoing_read_loop(self, remote: Remote, conn: Connection):
        """The dialed link mostly carries our sends; inbound on it is
        control traffic (pongs) or a peer answering on our link."""
        while conn.alive:
            payload = await conn.read_frame(self.msg_len_limit)
            if payload is None:
                conn.close()
                break
            self._dispatch_frame(payload, remote.name, conn)
        if remote.conn is conn:
            remote.conn = None
            self._emit_connecteds()

    # ---------------------------------------------------------- tx path

    def send(self, msg_dict: dict, dst=None):
        """Enqueue; dst None = broadcast, str or list of names."""
        if self.wire_tap is not None:
            routed = self.wire_tap.on_send(msg_dict, dst)
            if routed is not None:
                for m, d in routed:
                    self._send_untapped(m, d)
                return
        self._send_untapped(msg_dict, dst)

    def _send_untapped(self, msg_dict: dict, dst=None):
        raw = serializer.serialize(msg_dict)
        if len(raw) > self.msg_len_limit:
            logger.warning("%s: dropping oversized %dB message",
                           self.name, len(raw))
            return
        if dst is None:
            dsts = list(self.remotes)
        elif isinstance(dst, str):
            dsts = [dst]
        else:
            dsts = list(dst)
        for name in dsts:
            remote = self.remotes.get(name)
            if remote is None:
                logger.info("%s: no remote %s", self.name, name)
                continue
            remote.outbox.append(raw)

    def flush_outboxes(self) -> int:
        """Coalesce each remote's outbox into signed BATCH frames
        (reference batched.py:91 flushOutBoxes). → messages flushed."""
        flushed = 0
        for remote in self.remotes.values():
            if not remote.outbox:
                continue
            if not remote.is_connected:
                # bound memory while disconnected
                while len(remote.outbox) > 10000:
                    remote.outbox.popleft()
                continue
            msgs = list(remote.outbox)
            remote.outbox.clear()
            flushed += len(msgs)
            try:
                if len(msgs) == 1:
                    self._count_sent(len(msgs[0]))
                    remote.conn.send_frame(msgs[0])
                else:
                    with self.metrics.measure_time(_ENC_TIME):
                        frames = self._make_batches(msgs)
                    for frame in frames:
                        self._count_sent(len(frame))
                        remote.conn.send_frame(frame)
            except Exception:
                logger.info("%s: send to %s failed; dropping link",
                            self.name, remote.name)
                remote.disconnect()
                remote.outbox.extendleft(reversed(msgs))
                flushed -= len(msgs)
        self._emit_connecteds()
        return flushed

    def _count_sent(self, nbytes: int):
        self.metrics.add_event(_BYTES_SENT, nbytes)

    def _make_batches(self, msgs: List[bytes]) -> List[bytes]:
        """Pack serialized messages into signed batches under the size
        limit (reference prepare_batch.py split_messages_on_batches) —
        the packing rules live in pack_message_groups, shared with the
        client-reply coalescer."""
        frames = []
        for kind, val in pack_message_groups(
                msgs, self.msg_len_limit - 512, self.msg_len_limit,
                who=self.name):
            frames.append(val if kind == 'raw' else self._seal_batch(val))
        return frames

    def _seal_batch(self, group: List[bytes]) -> bytes:
        if len(group) == 1:
            return group[0]
        sig = b58encode(self.keys.sign(b"".join(group)))
        return serializer.serialize({
            OP_FIELD_NAME: BATCH_OP, "messages": group, "signature": sig})


class ClientStack(StackBase):
    """Client-facing listener (reference ClientZStack: one listener,
    anonymous-encrypted clients, connection limit protection)."""

    def __init__(self, name: str, ha: HA, keys: NodeKeys,
                 config: Optional[Config] = None):
        super().__init__(name, ha, keys, config)
        self._clients: Dict[str, Connection] = {}
        self._order: deque = deque()  # client ids, accept order
        self._counter = 0
        # per-client outbox for tick-coalesced replies: a committed
        # batch produces hundreds of Replies to the same client, and
        # one AEAD frame per Reply made the reply path the measured
        # wall of the multi-process pool (~150 us/reply). Queued sends
        # coalesce into BATCH envelopes at flush (reference batched.py
        # does this for the node stack; the client stack needs it just
        # as much under load)
        self._outboxes: Dict[str, List[bytes]] = {}

    def _close_connections(self):
        for conn in list(self._clients.values()):
            conn.close()
        self._clients.clear()
        self._order.clear()

    async def _on_accept(self, reader, writer):
        try:
            responder = Responder(self.keys.sk, allowed_vks=None,
                                  allow_anonymous=True)
            m1 = await _handshake_frames(reader, writer, True)
            m2 = responder.consume_message1(m1)
            m3 = await _handshake_frames(reader, writer, True, payload=m2)
            responder.consume_message3(m3)
        except (HandshakeError, asyncio.TimeoutError, ConnectionError,
                OSError, asyncio.IncompleteReadError) as e:
            logger.info("%s: client handshake failed: %s", self.name, e)
            writer.close()
            return
        self._counter += 1
        peer = writer.get_extra_info("peername") or ("?", 0)
        client_id = "client:{}:{}#{}".format(peer[0], peer[1], self._counter)
        conn = Connection(reader, writer, responder.session(), client_id)
        self._clients[client_id] = conn
        self._order.append(client_id)
        self._enforce_connection_limit()
        while conn.alive:
            payload = await conn.read_frame(self.msg_len_limit)
            if payload is None:
                conn.close()
                break
            self._enqueue_wire(payload, client_id)
        self._clients.pop(client_id, None)

    def _enforce_connection_limit(self):
        limit = self.config.MAX_CONNECTED_CLIENTS_NUM
        while len(self._clients) > limit and self._order:
            victim = self._order.popleft()
            conn = self._clients.pop(victim, None)
            if conn is not None:
                logger.info("%s: evicting client %s (connection limit)",
                            self.name, victim)
                conn.close()

    def send_to_client(self, client_id: str, msg_dict: dict) -> bool:
        """Immediate single-frame send (scripts/net_diag echo; tests).
        Production replies go through queue_to_client — do not mix the
        two for one client in the same tick or replies can reorder
        relative to the queued batch."""
        conn = self._clients.get(client_id)
        if conn is None or not conn.alive:
            return False
        try:
            conn.send_frame(serializer.serialize(msg_dict))
            return True
        except Exception:
            conn.close()
            self._clients.pop(client_id, None)
            return False

    def queue_to_client(self, client_id: str, msg_dict: dict) -> bool:
        """Coalescing variant of send_to_client: the message rides the
        next flush_client_outboxes() as part of a BATCH envelope."""
        conn = self._clients.get(client_id)
        if conn is None or not conn.alive:
            return False
        self._outboxes.setdefault(client_id, []).append(
            serializer.serialize(msg_dict))
        return True

    def flush_client_outboxes(self) -> int:
        """One frame (or a few, under the size limit) per client per
        tick instead of one per message. Client batches are NOT signed —
        the AEAD channel already authenticates the node end-to-end
        (unlike node-stack batches, which peers re-verify by verkey).
        Packing rules (incl. the oversize-drop guard) come from
        pack_message_groups, shared with the node stack."""
        if not self._outboxes:
            return 0
        flushed = 0
        outboxes, self._outboxes = self._outboxes, {}
        for client_id, msgs in outboxes.items():
            conn = self._clients.get(client_id)
            if conn is None or not conn.alive:
                # reply loss under churn must be diagnosable
                logger.debug(
                    "%s: dropping %d queued repl(y/ies) for %s — "
                    "connection gone before flush", self.name, len(msgs),
                    client_id)
                continue
            try:
                for kind, val in pack_message_groups(
                        msgs, self.msg_len_limit - 512,
                        self.msg_len_limit, who=self.name):
                    if kind == 'raw' or len(val) == 1:
                        conn.send_frame(val if kind == 'raw' else val[0])
                    else:
                        conn.send_frame(serializer.serialize(
                            {OP_FIELD_NAME: BATCH_OP, "messages": val}))
                flushed += len(msgs)
            except Exception:
                logger.debug(
                    "%s: connection to %s died mid-flush — dropping its "
                    "%d-message outbox", self.name, client_id, len(msgs))
                conn.close()
                self._clients.pop(client_id, None)
        return flushed


class ClientConnection:
    """Dialing side for wallets/tests: anonymous encrypted channel to a
    node's client listener."""

    def __init__(self, ha: HA, expected_verkey: Optional[bytes] = None):
        self.ha = ha
        self._expected_vk = expected_verkey
        self.conn: Optional[Connection] = None
        self.rx: deque = deque()
        self._reader_task = None

    async def connect(self):
        reader, writer = await asyncio.open_connection(*self.ha)
        try:
            initiator = Initiator(None, expected_peer_vk=self._expected_vk)
            m2 = await _handshake_frames(reader, writer, True,
                                         payload=initiator.message1())
            m3 = initiator.consume_message2(m2)
            await _handshake_frames(reader, writer, False, payload=m3)
        except BaseException:
            # a failed handshake must not leak the socket
            writer.close()
            raise
        self.conn = Connection(reader, writer, initiator.session(), "client")
        self._reader_task = asyncio.get_event_loop().create_task(
            self._read_loop())

    async def _read_loop(self):
        while self.conn is not None and self.conn.alive:
            try:
                payload = await self.conn.read_frame(Config.MSG_LEN_LIMIT)
            except Exception:
                # oversize/corrupt frame or transport error: the link is
                # unusable — close it so owners polling conn.alive
                # (NetworkedPoolClient.pump) redial instead of hanging
                # on a dead reader task forever
                logger.info("client read loop failed; closing link",
                            exc_info=True)
                self.conn.close()
                break
            if payload is None:
                # peer went away: mark the link dead so owners polling
                # `conn.alive` (NetworkedPoolClient.pump) can redial
                self.conn.close()
                break
            try:
                msg = serializer.deserialize(payload)
                if isinstance(msg, dict) and \
                        msg.get(OP_FIELD_NAME) == BATCH_OP:
                    # coalesced node->client frame: unpack in order;
                    # one undecodable entry costs ONE message (same
                    # blast radius as un-coalesced frames), not the
                    # tail of the envelope
                    for raw in msg.get("messages", []):
                        try:
                            self.rx.append(serializer.deserialize(
                                raw if isinstance(raw, bytes)
                                else bytes(raw)))
                        except Exception:
                            logger.warning(
                                "undecodable entry in client batch "
                                "frame - skipped")
                else:
                    self.rx.append(msg)
            except Exception:
                pass

    def send(self, msg_dict: dict):
        self.conn.send_frame(serializer.serialize(msg_dict))

    def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self.conn is not None:
            self.conn.close()
