"""Per-node span tracer — the flight-recorder core.

The metrics accumulators (utils/metrics.py) answer "how much time does
stage X cost in aggregate"; this tracer answers the CAUSAL question —
where one specific 3PC batch spent its time across the pool, and
whether the device seams were pipelined or idle between dispatches.

Design constraints (why this is not just `logging` with timestamps):

* Fixed cost per record. Every span is one tuple written into a slot of
  a PREALLOCATED ring buffer — no allocation growth, no I/O, no
  serialization on the hot path. When the buffer wraps, the oldest
  records are overwritten: a flight recorder keeps the newest history,
  which is the part that explains the failure/stall you just observed.
* Off by default, free when off. Instrumented call sites hold a
  `NullTracer` whose `span()` returns one shared no-op context manager —
  the disabled cost is a single attribute call, bench-gated to low
  single-digit percent even when enabled (bench.py tracing_overhead).
* Thread-safe. The verify daemon records from a worker thread while its
  asyncio loop coalesces; slot claims take a lock (the write itself is
  one tuple store, so the critical section is tiny).
* Injectable clock. Tests pin a fake clock for deterministic export;
  production uses `perf_counter`, which is shared by every tracer in a
  process — so a sim pool's per-node buffers merge into one coherent
  pool-wide timeline with no clock alignment step.
* Dual clocks for cross-process alignment. `clock_pair()` samples the
  perf-counter AND an injectable wall clock in one call; the exporter
  records the pair as a `clock_sync` event at flush so FILE-mode
  consumers (scripts/pool_journey over Chrome dumps from different
  processes) can re-anchor each node's perf timeline onto shared wall
  time. In-process merges never need it, and `NullTracer` stays free.

Record shape (one tuple per event, fixed arity):

    (kind, name, category, t0, t1, key, args)

    kind: "X" complete span | "i" instant | "C" counter sample
    key:  correlation key — request digest for intake/propagate spans,
          "viewNo:ppSeqNo" for 3PC phases (see docs/observability.md)
    args: payload dict (batch sizes, queue depths) or None

Categories below become per-node tracks in the Perfetto export.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

# span categories: one Perfetto track per category per node
CAT_INTAKE = "intake"        # client request validation + acceptance
CAT_PROPAGATE = "propagate"  # PROPAGATE gossip + quorum finalisation
CAT_3PC = "3pc"              # PrePrepare/Prepare/Commit/Order
CAT_EXECUTE = "execute"      # batch apply + durable commit
CAT_DEVICE = "device"        # accelerator dispatch/collect seams
CAT_BLS = "bls"              # BLS share aggregation
CAT_REPLY = "reply"          # reply construction + audit paths
CAT_RECOVERY = "recovery"    # view change / catchup / breaker lifecycle

Record = Tuple[str, str, str, float, Optional[float], Optional[str],
               Optional[dict]]


class _SpanCtx:
    """One open span: records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_key", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 key: Optional[str], args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._key = key
        self._args = args

    def __enter__(self) -> "_SpanCtx":
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        tracer = self._tracer
        tracer._record((
            "X", self._name, self._cat, self._t0, tracer._clock(),
            self._key, self._args))
        return False

    def add(self, **args) -> None:
        """Attach payload discovered mid-span (e.g. a batch size known
        only after validation)."""
        if self._args is None:
            self._args = {}
        self._args.update(args)


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def add(self, **args) -> None:
        pass


_NULL_CTX = _NullCtx()


class NullTracer:
    """The default every instrumented component holds: the hot path is a
    no-op attribute call returning one shared context manager."""

    __slots__ = ("name",)
    enabled = False

    def __init__(self, name: str = ""):
        self.name = name

    def span(self, name, cat="", key=None, **args) -> _NullCtx:
        return _NULL_CTX

    def instant(self, name, cat="", key=None, **args) -> None:
        pass

    def counter(self, name, value, cat="") -> None:
        pass

    def clock_pair(self) -> Tuple[float, float]:
        return (0.0, 0.0)

    def spans(self) -> List[Record]:
        return []

    def clear(self) -> None:
        pass

    def stats(self) -> dict:
        return {"enabled": False, "capacity": 0, "recorded": 0,
                "buffered": 0, "dropped": 0}


class Tracer:
    """Ring-buffer span recorder for one node (or one daemon)."""

    __slots__ = ("name", "_capacity", "_buf", "_idx", "_written",
                 "_clock", "_wall_clock", "_lock")
    enabled = True

    def __init__(self, name: str = "", capacity: int = 1 << 16,
                 clock=time.perf_counter, wall_clock=time.time):
        self.name = name
        self._capacity = max(1, int(capacity))
        self._buf: List[Optional[Record]] = [None] * self._capacity
        self._idx = 0           # next slot to overwrite
        self._written = 0       # total records ever (>= buffered)
        self._clock = clock
        self._wall_clock = wall_clock
        self._lock = threading.Lock()

    # ------------------------------------------------------------ record

    def _record(self, rec: Record) -> None:
        with self._lock:
            self._buf[self._idx] = rec
            self._idx = (self._idx + 1) % self._capacity
            self._written += 1

    def span(self, name: str, cat: str = "", key: Optional[str] = None,
             **args) -> _SpanCtx:
        """Context manager timing one complete span."""
        return _SpanCtx(self, name, cat, key, args or None)

    def instant(self, name: str, cat: str = "",
                key: Optional[str] = None, **args) -> None:
        """Zero-duration marker (quorum reached, request accepted)."""
        t = self._clock()
        self._record(("i", name, cat, t, t, key, args or None))

    def counter(self, name: str, value, cat: str = "") -> None:
        """Counter sample (queue depth, batch size) — rendered by
        Perfetto as a stacked counter track."""
        self._record(("C", name, cat, self._clock(), None, None,
                      {name: value}))

    def clock_pair(self) -> Tuple[float, float]:
        """(perf_counter, wall) sampled back to back — the anchor pair
        wire stamps and flush-time `clock_sync` events carry so
        cross-process consumers can align this tracer's perf timeline
        onto wall time."""
        return (self._clock(), self._wall_clock())

    # -------------------------------------------------------------- read

    def spans(self) -> List[Record]:
        """Buffered records, oldest → newest. After a wrap only the
        newest `capacity` records survive — flight-recorder semantics."""
        with self._lock:
            if self._written < self._capacity:
                return list(self._buf[:self._idx])
            return list(self._buf[self._idx:]) + list(self._buf[:self._idx])

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self._capacity
            self._idx = 0
            self._written = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "capacity": self._capacity,
                "recorded": self._written,
                "buffered": min(self._written, self._capacity),
                "dropped": max(0, self._written - self._capacity),
            }
