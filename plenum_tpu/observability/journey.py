"""Journey plane — per-request cross-node causal records and quorum
critical-path attribution.

The flight recorder (tracing.py) answers "what did THIS node spend its
time on"; the telemetry plane answers "what are the distributions".
Neither answers the question that decides where pipeline work goes
next: for one ordered request, WHERE did its wall-clock go ACROSS the
pool — the wire, waiting for the slowest quorum voter, or local
stages?  This module joins the per-node tracer buffers (or an exported
Chrome trace document — both forms carry the same records) with the
wire-carried trace stamps (flat_wire KIND_TRACE / typed ``traceCtx``)
into:

* **per-request journeys**, keyed by request digest and joined to the
  owning 3PC batch through the ``order`` span's ``digests`` arg:
  client intake (``request_accepted``) → propagate-quorum close
  (``propagate_quorum``, naming the relay whose vote supplied the
  f+1'th) → per-node PRE-PREPARE receive (``pp_process``) → prepare/
  commit quorum close (``prepare_quorum``/``commit_quorum``, naming
  the closing voter) → ``order`` → ``reply``, per node;
* **per-directed-link clock model**: every stamped envelope yields one
  (send perf/wall, receive perf/wall) sample; per-node wall offsets
  (median of ``wall − perf`` across wire samples) align timelines
  recorded by different processes, and the remaining per-link offset
  asymmetry — ``skew(a→b) = (median Δ(a→b) − median Δ(b→a)) / 2`` —
  separates residual clock skew from one-way delay, so each hop gets a
  defensible one-way delay estimate even without synchronised clocks;
* **per-batch critical path**: the node whose ``order`` completed
  last, the phase chain that fed it, and the last hop (peer → node,
  with its delay estimate) that closed the final quorum — plus a
  breakdown of the ordered end-to-end time into wire / straggler-wait
  / local-stage shares (the pool25 bench headline and the input to
  the pipeline-parallel roadmap item).

Everything here is ADVISORY read-side joinery: it consumes recorded
events after the fact and touches no consensus state. A pool run with
stripped or corrupted stamps (adversary taps degrade the outbox to
per-message sends, which carry no stamps) simply yields journeys with
no link samples — per-node phase records survive, hop delays read 0,
and nothing fails.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# journey phases in nominal money-path order. NOTE: only a subset of
# pairwise orderings is causally guaranteed (quorum closes can precede
# a node's own pp_recv under out-of-order delivery) — see
# causal_violations for the exact DAG that is checked
PHASES = ("intake", "propagate_close", "pp_recv", "prepare_close",
          "commit_close", "order", "reply")


# --------------------------------------------------- event normalization

def _events_from_tracers(tracers: Iterable) -> Dict[str, List[tuple]]:
    """Live Tracer buffers → node → [(kind, name, t0, t1, key, args)].
    Timestamps stay in the tracers' perf_counter seconds."""
    by_node: Dict[str, List[tuple]] = {}
    for tracer in tracers:
        if tracer is None:
            continue
        recs = tracer.spans()
        if not recs:
            continue
        out = by_node.setdefault(tracer.name or "node", [])
        for kind, name, _cat, t0, t1, key, args in recs:
            out.append((kind, name, t0, t1, key, args or {}))
    return by_node


def _events_from_chrome(doc: dict) -> Dict[str, List[tuple]]:
    """Exported Chrome trace document → the same per-node event lists
    (microsecond ts → seconds)."""
    events = doc.get("traceEvents", [])
    pid_names = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    by_node: Dict[str, List[tuple]] = {}
    for e in events:
        ph = e.get("ph")
        if ph not in ("X", "i"):
            continue
        node = pid_names.get(e.get("pid"), str(e.get("pid")))
        t0 = e.get("ts", 0) * 1e-6
        t1 = t0 + e.get("dur", 0) * 1e-6
        args = dict(e.get("args") or {})
        key = args.pop("key", None)
        by_node.setdefault(node, []).append(
            (ph, e.get("name", ""), t0, t1, key, args))
    return by_node


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


# ----------------------------------------------------- clock/link model

class _ClockModel:
    """Per-node wall alignment + per-directed-link skew/delay, built
    solely from ``wire_recv`` instants (each carries the SENDER's
    perf/wall pair out of the stamp next to the receiver's own)."""

    def __init__(self, by_node: Dict[str, List[tuple]]):
        offset_samples: Dict[str, List[float]] = {}
        link_raw: Dict[Tuple[str, str], List[float]] = {}
        recv_index: Dict[str, List[tuple]] = {}
        for node, events in by_node.items():
            for kind, name, t0, _t1, _key, args in events:
                if kind != "i" or name != "wire_recv":
                    continue
                origin = args.get("origin")
                sent_perf = args.get("sent_perf")
                sent_wall = args.get("sent_wall")
                recv_wall = args.get("recv_wall")
                if origin is None or sent_perf is None:
                    continue
                if sent_wall:
                    offset_samples.setdefault(origin, []).append(
                        sent_wall - sent_perf)
                if recv_wall:
                    offset_samples.setdefault(node, []).append(
                        recv_wall - t0)
                link_raw.setdefault((origin, node), []).append(
                    (t0, sent_perf))
                recv_index.setdefault(node, []).append(
                    (t0, origin, args.get("frm", origin)))
        self.wall_offset: Dict[str, float] = {
            n: _median(s) for n, s in offset_samples.items()}
        # nodes never seen on the wire align to the pool median (exact
        # for single-process traces, where every offset is equal)
        self._default_offset = _median(list(self.wall_offset.values()))
        # aligned send→recv deltas per directed link
        deltas: Dict[Tuple[str, str], List[float]] = {}
        for (a, b), samples in link_raw.items():
            deltas[(a, b)] = [
                (t_recv + self.offset(b)) - (sp + self.offset(a))
                for t_recv, sp in samples]
        medians = {lk: _median(ds) for lk, ds in deltas.items()}
        self.skew: Dict[Tuple[str, str], float] = {}
        self.delay: Dict[Tuple[str, str], float] = {}
        self.samples: Dict[Tuple[str, str], int] = {}
        for (a, b), med in medians.items():
            rev = medians.get((b, a))
            skew = (med - rev) / 2.0 if rev is not None else 0.0
            self.skew[(a, b)] = skew
            self.delay[(a, b)] = max(0.0, med - skew)
            self.samples[(a, b)] = len(deltas[(a, b)])
        for node, idx in recv_index.items():
            idx.sort()
        self._recv_index = recv_index

    def offset(self, node: str) -> float:
        return self.wall_offset.get(node, self._default_offset)

    def aligned(self, node: str, t: Optional[float]) -> Optional[float]:
        return None if t is None else t + self.offset(node)

    def hop_delay(self, frm: str, to: str) -> float:
        """Median one-way delay estimate for a directed link, seconds
        (0.0 when the link never carried a stamp — degraded mode)."""
        return self.delay.get((frm, to), 0.0)

    def last_hop_before(self, node: str, frm: str,
                        t_local: float) -> Optional[float]:
        """Receive time (local clock) of the last stamped envelope
        ``frm → node`` at or before ``t_local`` — the envelope that
        plausibly carried the event closing a quorum at ``t_local``."""
        best = None
        for t_recv, origin, sender in self._recv_index.get(node, ()):
            if t_recv > t_local + 1e-9:
                break
            if origin == frm or sender == frm:
                best = t_recv
        return best

    def links_report(self) -> Dict[str, dict]:
        out = {}
        for (a, b), d in sorted(self.delay.items()):
            out["%s->%s" % (a, b)] = {
                "samples": self.samples[(a, b)],
                "delay_ms": round(d * 1e3, 4),
                "skew_ms": round(self.skew[(a, b)] * 1e3, 4),
            }
        return out


# ------------------------------------------------------------- the join

def _phase_records(by_node: Dict[str, List[tuple]]):
    """One pass over every node's events → the join indexes."""
    intake: Dict[str, List[Tuple[float, str]]] = {}       # digest
    prop: Dict[str, Dict[str, dict]] = {}                 # digest→node
    digest_to_batch: Dict[str, str] = {}
    batches: Dict[str, dict] = {}
    # (viewNo:ppSeqNo) → [(pp digest, observer, sender, t)] — every
    # PRE-PREPARE a node processed, INCLUDING ones it went on to
    # discard as conflicting: the raw material for equivocation
    # evidence (an equivocating primary's second digest never lands in
    # any prePrepares store, but its pp_process span is on the record)
    pp_obs: Dict[str, List[tuple]] = {}

    def batch(key: str) -> dict:
        return batches.setdefault(key, {
            "key": key, "digests": [], "primary": None,
            "pp_create": None, "nodes": {}, "stragglers": []})

    def node_rec(key: str, node: str) -> dict:
        return batch(key)["nodes"].setdefault(node, {})

    gateway: Dict[str, List[Tuple[float, str]]] = {}      # digest

    for node, events in by_node.items():
        for kind, name, t0, t1, key, args in events:
            if name == "request_accepted" and key:
                intake.setdefault(key, []).append((t0, node))
            elif name == "gateway_admit" and key:
                gateway.setdefault(key, []).append((t0, node))
            elif name == "propagate_quorum" and key:
                prop.setdefault(key, {})[node] = {
                    "t": t0, "closer": args.get("closer"),
                    "votes": args.get("votes")}
            elif name == "pp_create" and key:
                b = batch(key)
                b["primary"] = node
                b["pp_create"] = {"node": node, "t0": t0, "t1": t1}
                node_rec(key, node)["pp_recv"] = t1
            elif name == "pp_process" and key:
                node_rec(key, node).setdefault("pp_recv", t0)
                if args.get("digest"):
                    pp_obs.setdefault(key, []).append(
                        (args["digest"], node, args.get("frm"), t0))
            elif name in ("prepare_quorum", "commit_quorum") and key:
                phase = name.split("_")[0]
                rec = node_rec(key, node)
                rec[phase + "_close"] = t0
                rec[phase + "_closer"] = args.get("closer")
            elif name in ("prepare_vote_late", "commit_vote_late") and key:
                batch(key)["stragglers"].append({
                    "phase": name.split("_")[0], "node": node,
                    "frm": args.get("frm"), "t": t0})
            elif name == "order" and key:
                # the ordering DECISION anchors at span start: the
                # executor's commit + reply run nested inside this
                # span, so its end is after the reply and would break
                # the causal chain
                rec = node_rec(key, node)
                rec.setdefault("order", t0)
                rec["order_end"] = t1
                for d in args.get("digests") or ():
                    digest_to_batch[d] = key
                    b = batch(key)
                    if d not in b["digests"]:
                        b["digests"].append(d)
            elif name == "ordered" and key:
                # replica-level Ordered emission — the preferred order
                # anchor when present (fires before the commit/reply
                # work the order span encloses)
                node_rec(key, node)["order"] = t0
            elif name == "reply" and key:
                node_rec(key, node)["reply"] = t1
    return intake, prop, digest_to_batch, batches, pp_obs, gateway


def _equivocations(pp_obs: Dict[str, List[tuple]],
                   clocks: _ClockModel) -> List[dict]:
    """(viewNo:ppSeqNo) slots where the pool processed CONFLICTING
    PRE-PREPARE digests → the evidence chain: which digests, observed
    by whom, from whom, when (aligned clock). Two distinct digests for
    one slot is the definition of primary equivocation — the exact
    artifact an invariant-failure dump needs to pin the culprit."""
    out: List[dict] = []
    for key, obs in sorted(pp_obs.items()):
        digests = sorted({d for d, _, _, _ in obs})
        if len(digests) < 2:
            continue
        chain = {}
        for d in digests:
            chain[d] = [
                {"observed_by": node, "frm": frm,
                 "t": clocks.aligned(node, t)}
                for dd, node, frm, t in sorted(
                    obs, key=lambda o: o[3]) if dd == d]
        out.append({"key": key, "digests": digests, "evidence": chain})
    return out


def _critical_path(b: dict, intake_t: Optional[Tuple[float, str]],
                   prop_close: Optional[dict],
                   clocks: _ClockModel) -> Optional[dict]:
    """The per-batch attribution: last node, its phase chain, the last
    hop, and the wire/straggler/local breakdown of ordered e2e."""
    nodes = b["nodes"]
    done = [(clocks.aligned(n, r["order"]), n) for n, r in nodes.items()
            if r.get("order") is not None]
    if not done:
        return None
    _t_last, last = max(done)
    rec = nodes[last]
    primary = b["primary"]
    al = clocks.aligned

    hops: List[dict] = []

    def hop(frm: Optional[str], phase: str) -> float:
        if not frm or frm == last:
            return 0.0
        d = clocks.hop_delay(frm, last)
        hops.append({"from": frm, "to": last, "phase": phase,
                     "delay_ms": round(d * 1e3, 4)})
        return d

    # chain timestamps on the last node (aligned domain)
    t_intake = intake_t[0] if intake_t else None
    t_prop = (prop_close or {}).get("t")
    t_pp_sent = al(primary, (b["pp_create"] or {}).get("t1")) \
        if primary else None
    t_pp = al(last, rec.get("pp_recv"))
    t_prep = al(last, rec.get("prepare_close"))
    t_com = al(last, rec.get("commit_close"))
    t_order = al(last, rec.get("order"))
    t_reply = al(last, rec.get("reply"))

    wire = 0.0
    if prop_close and prop_close.get("closer") and primary:
        wire += clocks.hop_delay(prop_close["closer"], primary) \
            if prop_close["closer"] != primary else 0.0
    if last != primary and primary:
        wire += hop(primary, "pp")
    prep_hop = hop(rec.get("prepare_closer"), "prepare")
    com_hop = hop(rec.get("commit_closer"), "commit")
    wire += prep_hop + com_hop

    def seg(name: str, a: Optional[float], z: Optional[float]):
        if a is None or z is None:
            return None
        return {"name": name, "ms": round(max(0.0, z - a) * 1e3, 4)}

    segments = [s for s in (
        seg("intake->propagate_close", t_intake, t_prop),
        seg("propagate_close->pp_sent", t_prop, t_pp_sent),
        seg("pp_sent->pp_recv", t_pp_sent, t_pp),
        seg("pp_recv->prepare_close", t_pp, t_prep),
        seg("prepare_close->commit_close", t_prep, t_com),
        seg("commit_close->order", t_com, t_order),
        seg("order->reply", t_order, t_reply),
    ) if s is not None]

    straggler = 0.0
    if t_pp is not None and t_prep is not None:
        straggler += max(0.0, (t_prep - t_pp) - prep_hop)
    if t_prep is not None and t_com is not None:
        straggler += max(0.0, (t_com - t_prep) - com_hop)

    t_end = t_reply if t_reply is not None else t_order
    e2e = (t_end - t_intake) if (t_intake is not None
                                 and t_end is not None) else None
    breakdown = None
    if e2e and e2e > 0:
        wire_pct = min(100.0, wire / e2e * 100.0)
        strag_pct = min(100.0 - wire_pct, straggler / e2e * 100.0)
        breakdown = {
            "e2e_ms": round(e2e * 1e3, 4),
            "wire_pct": round(wire_pct, 2),
            "straggler_pct": round(strag_pct, 2),
            "local_pct": round(100.0 - wire_pct - strag_pct, 2),
        }
    return {
        "node": last,
        "phase": "reply" if t_reply is not None else "order",
        "last_hop": hops[-1] if hops else None,
        "hops": hops,
        "segments": segments,
        "breakdown": breakdown,
    }


def _build(by_node: Dict[str, List[tuple]]) -> dict:
    clocks = _ClockModel(by_node)
    intake, prop, digest_to_batch, batches, pp_obs, gateway = \
        _phase_records(by_node)

    requests: Dict[str, dict] = {}
    degraded = not clocks.delay   # no stamped envelope anywhere
    for digest in sorted(set(intake) | set(prop) | set(digest_to_batch)
                         | set(gateway)):
        arrivals = sorted(
            (clocks.aligned(n, t), n) for t, n in intake.get(digest, ()))
        closes = sorted(
            ((clocks.aligned(n, rec["t"]), n, rec)
             for n, rec in prop.get(digest, {}).items()))
        bkey = digest_to_batch.get(digest)
        admits = sorted(
            (clocks.aligned(n, t), n) for t, n in gateway.get(digest, ()))
        requests[digest] = {
            "digest": digest,
            "batch": bkey,
            "gateway": ({"node": admits[0][1],
                         "t": admits[0][0]} if admits else None),
            "intake": ({"node": arrivals[0][1],
                        "t": arrivals[0][0]} if arrivals else None),
            "propagate_close": ({"node": closes[0][1], "t": closes[0][0],
                                 "closer": closes[0][2].get("closer"),
                                 "votes": closes[0][2].get("votes")}
                                if closes else None),
            "propagate_nodes": {n: clocks.aligned(n, rec["t"])
                                for n, rec in prop.get(digest, {}).items()},
        }

    for key, b in batches.items():
        first_intake = None
        prop_close_primary = None
        for digest in b["digests"]:
            r = requests.get(digest) or {}
            it = r.get("intake")
            if it and (first_intake is None or it["t"] < first_intake[0]):
                first_intake = (it["t"], it["node"])
            # the batch cannot form before its LAST digest finalises on
            # the primary — that propagate close gates pp_create
            pn = r.get("propagate_nodes") or {}
            t_primary = pn.get(b["primary"]) if b["primary"] else None
            if t_primary is not None and (
                    prop_close_primary is None
                    or t_primary > prop_close_primary["t"]):
                pc = (prop.get(digest) or {}).get(b["primary"]) or {}
                prop_close_primary = {"t": t_primary,
                                      "closer": pc.get("closer")}
        b["critical_path"] = _critical_path(
            b, first_intake, prop_close_primary, clocks)

    complete = sum(
        1 for r in requests.values()
        if r["batch"] and r["intake"] and r["propagate_close"]
        and all(rec.get("order") is not None
                for rec in batches[r["batch"]]["nodes"].values()))
    return {
        "nodes": sorted(by_node),
        "requests": requests,
        "batches": batches,
        "links": clocks.links_report(),
        "wall_offsets": {n: round(v, 6)
                         for n, v in sorted(clocks.wall_offset.items())},
        "complete_requests": complete,
        "degraded": degraded,
        "breakdown": pool_breakdown(batches),
        "equivocations": _equivocations(pp_obs, clocks),
        "_clocks": clocks,
    }


def pool_breakdown(batches: Dict[str, dict]) -> Optional[dict]:
    """Average the per-batch critical-path breakdowns → the pool-level
    wire / straggler / local shares (the bench headline)."""
    rows = [b["critical_path"]["breakdown"] for b in batches.values()
            if b.get("critical_path")
            and b["critical_path"].get("breakdown")]
    if not rows:
        return None
    n = len(rows)
    return {
        "batches": n,
        "e2e_ms_mean": round(sum(r["e2e_ms"] for r in rows) / n, 4),
        "wire_pct": round(sum(r["wire_pct"] for r in rows) / n, 2),
        "straggler_pct": round(
            sum(r["straggler_pct"] for r in rows) / n, 2),
        "local_pct": round(sum(r["local_pct"] for r in rows) / n, 2),
    }


def journeys_from_tracers(tracers: Iterable) -> dict:
    """Live per-node Tracer buffers → the journey report."""
    return _build(_events_from_tracers(tracers))


def journeys_from_chrome(doc: dict) -> dict:
    """Exported Chrome trace document (trace_view / scenario dumps) →
    the same journey report, reconstructed from the file."""
    return _build(_events_from_chrome(doc))


# -------------------------------------------------------------- checks

def causal_violations(report: dict) -> List[str]:
    """Check the report against what the money path genuinely
    guarantees, per node in the ALIGNED clock domain:

    * gateway admit ≤ intake ≤ propagate close (per request);
    * on the primary, the batch's gating propagate close ≤ pp_create
      (the batch cannot form before its last digest finalises);
    * pp_recv ≤ order, prepare_close ≤ order, commit_close ≤ order
      (ordering requires the PRE-PREPARE and both quorums);
    * order ≤ reply.

    Deliberately a DAG, not a chain: peers' PREPARE/COMMIT votes can
    land — and close a counted quorum — BEFORE this node's own copy of
    the PRE-PREPARE arrives (out-of-order delivery), so quorum closes
    are ordered only against ``order``, not against ``pp_recv`` or each
    other. → human-readable violation list; empty = the recorded
    history is causally consistent."""
    out: List[str] = []
    clocks = report.get("_clocks")
    eps = 1e-9
    for key, b in sorted((report.get("batches") or {}).items()):
        t_gate = None
        for digest in b["digests"]:
            r = (report.get("requests") or {}).get(digest) or {}
            it, pc = r.get("intake"), r.get("propagate_close")
            gw = r.get("gateway")
            if gw and it and it["t"] < gw["t"] - eps:
                out.append("%s: intake before gateway admit" % digest)
            if it and pc and pc["t"] < it["t"] - eps:
                out.append("%s: propagate close before intake" % digest)
            if pc and (t_gate is None or pc["t"] > t_gate):
                t_gate = pc["t"]
        for node, rec in sorted(b["nodes"].items()):
            al = (lambda t: clocks.aligned(node, t)) if clocks \
                else (lambda t: t)
            t_order = al(rec.get("order"))
            t_reply = al(rec.get("reply"))
            if node == b["primary"] and t_gate is not None:
                t_pp = al(rec.get("pp_recv"))
                if t_pp is not None and t_pp < t_gate - eps:
                    out.append(
                        "%s@%s: pp_create (%.6f) before propagate_close "
                        "(%.6f)" % (key, node, t_pp, t_gate))
            if t_order is not None:
                for name in ("pp_recv", "prepare_close", "commit_close"):
                    t = al(rec.get(name))
                    if t is not None and t_order < t - eps:
                        out.append(
                            "%s@%s: order (%.6f) before %s (%.6f)" % (
                                key, node, t_order, name, t))
            if t_reply is not None and t_order is not None \
                    and t_reply < t_order - eps:
                out.append("%s@%s: reply (%.6f) before order (%.6f)" % (
                    key, node, t_reply, t_order))
    return out


# ---------------------------------------------------------- exposition

def format_table(report: dict) -> str:
    """Human-readable journey report (the ``pool_journey`` CLI)."""
    lines = []
    reqs = report.get("requests") or {}
    lines.append("journeys: %d request(s), %d complete, %d batch(es)%s"
                 % (len(reqs), report.get("complete_requests", 0),
                    len(report.get("batches") or {}),
                    "  [DEGRADED: no wire stamps]"
                    if report.get("degraded") else ""))
    links = report.get("links") or {}
    if links:
        lines.append("links (median one-way delay, skew-corrected):")
        for name, l in links.items():
            lines.append("  %-22s %8.3f ms  (skew %+.3f ms, n=%d)" % (
                name, l["delay_ms"], l["skew_ms"], l["samples"]))
    for eq in report.get("equivocations") or ():
        lines.append("EQUIVOCATION at %s: %d conflicting digests" % (
            eq["key"], len(eq["digests"])))
        for d in eq["digests"]:
            obs = eq["evidence"][d]
            lines.append("  %s observed by %s" % (
                d[:16], ", ".join(sorted(
                    {"%s (from %s)" % (o["observed_by"], o["frm"])
                     for o in obs}))))
    for key, b in sorted((report.get("batches") or {}).items()):
        cp = b.get("critical_path") or {}
        bd = cp.get("breakdown") or {}
        lines.append("batch %-8s primary=%s digests=%d last=%s/%s" % (
            key, b.get("primary"), len(b["digests"]),
            cp.get("node"), cp.get("phase")))
        hop = cp.get("last_hop")
        if hop:
            lines.append("  last hop: %s -> %s (%s, %.3f ms)" % (
                hop["from"], hop["to"], hop["phase"], hop["delay_ms"]))
        for s in cp.get("segments") or ():
            lines.append("  %-28s %10.3f ms" % (s["name"], s["ms"]))
        if bd:
            lines.append("  e2e %.3f ms = wire %.1f%% + straggler %.1f%%"
                         " + local %.1f%%" % (
                             bd["e2e_ms"], bd["wire_pct"],
                             bd["straggler_pct"], bd["local_pct"]))
    bd = report.get("breakdown")
    if bd:
        lines.append(
            "pool critical path (%d batches): e2e %.3f ms mean = "
            "wire %.1f%% + straggler %.1f%% + local %.1f%%" % (
                bd["batches"], bd["e2e_ms_mean"], bd["wire_pct"],
                bd["straggler_pct"], bd["local_pct"]))
    return "\n".join(lines)


def to_json(report: dict) -> dict:
    """The report minus the internal clock model (JSON-safe)."""
    return {k: v for k, v in report.items() if not k.startswith("_")}
