"""Consensus flight recorder — span tracing + Perfetto export.

`tracing` owns the per-node ring-buffer Tracer (and the free NullTracer
the rest of the codebase holds by default); `export` turns any set of
tracers into one Chrome trace-event (Perfetto-loadable) timeline with a
"pid" row per node and a track per span category. docs/observability.md
explains the span model and how to read the merged timeline.
"""
from plenum_tpu.observability.tracing import (  # noqa: F401
    CAT_3PC, CAT_BLS, CAT_DEVICE, CAT_EXECUTE, CAT_INTAKE, CAT_PROPAGATE,
    CAT_REPLY, NullTracer, Tracer)
