"""Observability plane — accumulators ride utils/metrics; this package
owns the other two instruments (docs/observability.md):

`tracing` owns the per-node ring-buffer Tracer (and the free NullTracer
the rest of the codebase holds by default); `export` turns any set of
tracers into one Chrome trace-event (Perfetto-loadable) timeline with a
"pid" row per node, a track per span category, and flow arrows pairing
stamped envelope sends with their receives; `telemetry` is the
always-on plane — latency histograms (p50/p99 on the ordered money
path), device-efficiency lane accounting at every bucket-padding
dispatch seam, pool-health gauges, Prometheus exposition; `budget`
turns recorded spans into per-stage host-ms budgets; `journey` joins
per-node buffers and wire-carried trace stamps into per-request
cross-node causal records and per-batch critical-path attribution.
"""
from plenum_tpu.observability.tracing import (  # noqa: F401
    CAT_3PC, CAT_BLS, CAT_DEVICE, CAT_EXECUTE, CAT_INTAKE, CAT_PROPAGATE,
    CAT_REPLY, NullTracer, Tracer)
from plenum_tpu.observability.telemetry import (  # noqa: F401
    TM, LogLinearHistogram, NullTelemetryHub, TelemetryHub,
    get_seam_hub, merged_snapshot, prometheus_text, set_seam_hub)
from plenum_tpu.observability.journey import (  # noqa: F401
    causal_violations, journeys_from_chrome, journeys_from_tracers,
    pool_breakdown)
