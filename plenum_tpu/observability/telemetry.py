"""Always-on telemetry plane — latency histograms, device-efficiency
accounting, pool health.

The third instrument next to the metrics accumulators (utils/metrics.py
— aggregate count/sum/min/max, no percentiles) and the flight recorder
(observability/tracing.py — causal span timelines, off by default).
This plane is ON by default and cheap enough to stay on in production
(bench.py ``telemetry_overhead`` A/Bs the identical 4-node pool with it
on vs off and gates the cost under 2%): a serving tier is judged on
tail latency, and a padding-efficiency regression at a device seam is
the consensus-stack analog of an MFU drop — both must be *recorded
trajectories*, not post-hoc debugging sessions.

Three metric families:

* **End-to-end latency histograms** on the money path: intake→reply
  per ordered request (``TM.ORDERED_E2E_MS``) plus per-stage
  durations (propagate-quorum wait, 3PC, fused dispatch window,
  execute, reply), keyed by the same request digests the flight
  recorder stamps.
* **Device-efficiency accounting** at every dispatch half: each seam
  that bucket-pads its batches (verifier hub/daemon ed25519, sha256 /
  sha3 block buckets, mesh shard padding, merkle append levels, BLS
  job axis, trie_jax levels) records useful rows vs padded lanes per
  launch — ``lane_occupancy`` = useful/lanes — plus dispatch→collect
  round-trip and inter-dispatch idle-gap histograms, and compile
  events (a new bucket shape per seam is counted and its first-call
  latency recorded, so a shape explosion reads as a number instead of
  a mystery stall).
* **Pool health**: backlog depth, stash sizes, request-queue depth
  gauges, and view-change / catchup counters bridged from the
  recovery lane.

Design constraints:

* **Preallocated log-linear histograms.** One fixed numpy int64
  bucket array per histogram: SUB linear sub-buckets per power-of-two
  octave from ``lo`` up, so any recorded value lands in a bucket whose
  relative width is at most 1/SUB — quantile readout (p50/p95/p99/
  p999) has bounded relative error by construction, and two nodes'
  histograms merge by adding count arrays (pool-wide percentiles are
  exact merges, not approximations of approximations).
* **Lock-cheap record.** One uncontended lock around a handful of
  scalar updates (~100 ns); no allocation, no I/O, no string
  formatting on the hot path.
* **Registry-constant names.** Every metric name is a ``TM`` constant
  and every seam name a ``SEAM_*`` constant; lint rule PT009 flags
  dynamically-built names at record sites (unbounded cardinality),
  and the dead-name test pins every registry entry to a live
  recording site under plenum_tpu/.

Exposition: ``snapshot()`` (node-local dict), ``merge`` (pool-wide
aggregation in sim), ``prometheus_text`` / ``write_prometheus``
(Prometheus text format, written per flush interval when
``Config.TELEMETRY_PROM_DIR`` is set), a ``Telemetry`` section in
``ValidatorNodeInfoTool.info``, counter tracks on the merged Perfetto
timeline (observability/export.py), and the ``scripts/telemetry_stats``
table renderer.
"""
from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

import numpy as np


class TM:
    """Telemetry metric name registry. Record sites MUST use these
    constants — a dynamically-built name at a record site is unbounded
    cardinality (lint PT009) and invisible to the dead-name test."""

    # ---- end-to-end latency (money path; milliseconds, wall clock)
    ORDERED_E2E_MS = "ordered_e2e_ms"          # intake accept -> reply
    STAGE_PROPAGATE_MS = "stage_propagate_ms"  # accept -> quorum forward
    STAGE_3PC_MS = "stage_3pc_ms"              # PP create/process -> order
    STAGE_DISPATCH_MS = "stage_dispatch_ms"    # fused device window
    STAGE_EXECUTE_MS = "stage_execute_ms"      # batch apply (speculative)
    STAGE_COMMIT_MS = "stage_commit_ms"        # batch commit (durable)
    STAGE_REPLY_MS = "stage_reply_ms"          # reply construct + proofs

    # ---- conflict-lane executor (server/executor.py): per-batch lane
    # accounting — how parallel the declared-key partition actually is
    EXEC_LANES_PER_BATCH = "exec_lanes_per_batch"    # hist: lane count
    EXEC_CONFLICT_PCT = "exec_conflict_pct"          # hist: 0..100
    EXEC_SERIAL_FALLBACK = "exec_serial_fallback_reqs"  # counter

    # ---- wire plane (flat zero-copy codec; recorded into the SEAM
    # hub — the wire is a process-shared resource like the device
    # seams, and pool-wide reports merge it the same way)
    WIRE_BYTES_SENT = "wire_bytes_sent"        # counter: flat payload B
    WIRE_BYTES_RECV = "wire_bytes_recv"        # counter: flat payload B
    WIRE_ENV_BYTES_3PC = "wire_env_bytes_three_pc"      # hist: env size
    WIRE_ENV_BYTES_PROPAGATE = "wire_env_bytes_propagate"
    WIRE_VOTE_BYTES_PREPARE = "wire_vote_bytes_prepare"  # hist: B/vote
    WIRE_VOTE_BYTES_COMMIT = "wire_vote_bytes_commit"
    WIRE_VOTE_BYTES_PREPREPARE = "wire_vote_bytes_preprepare"
    WIRE_MALFORMED = "wire_malformed"          # counter: rejected envs

    # ---- gateway tier (plenum_tpu/gateway/): the client-facing front
    # door — admission verdicts, shed ladder, signed-read cache and the
    # gateway-side tail the open-loop bench gates on
    GATEWAY_E2E_MS = "gateway_e2e_ms"            # hist: arrive→outcome
    GATEWAY_ADMITTED = "gateway_admitted"        # counter: entered pool
    GATEWAY_SHED_READS = "gateway_shed_reads"    # counter: degraded 1st
    GATEWAY_SHED_WRITES = "gateway_shed_writes"  # counter: degraded 2nd
    GATEWAY_DEDUP_HITS = "gateway_dedup_hits"    # counter: dup payloads
    GATEWAY_SIG_REJECTS = "gateway_sig_rejects"  # counter: pre-screen
    GATEWAY_CACHE_HITS = "gateway_cache_hits"    # counter: signed reads
    GATEWAY_CACHE_MISSES = "gateway_cache_misses"  # counter
    GATEWAY_SHED_SENDERS = "gateway_shed_senders"  # counter: wire abuse
    GATEWAY_BACKLOG = "gateway_backlog"          # gauge: in-flight
    GATEWAY_LANES_PER_BATCH = "gateway_lanes_per_batch"  # hist

    # ---- journey plane (observability/journey.py): quorum critical-
    # path attribution on the money path. The margin histogram records,
    # per ordered batch and phase, how late the LAST counted straggler
    # vote landed after the quorum had already closed (0 = the closing
    # vote was also the last); the lateness family is the same signal
    # split per peer (labeled histogram — the label is a VALUE, the
    # family name stays a registry constant, PT009-clean), naming which
    # peers consistently trail the quorum.
    QUORUM_CLOSE_MARGIN_MS = "quorum_close_margin_ms"
    PEER_VOTE_LATENESS_MS = "peer_vote_lateness_ms"  # labeled by peer

    # ---- pool health
    BACKLOG_DEPTH = "backlog_depth"            # gauge: in-flight requests
    REQUEST_QUEUE_DEPTH = "request_queue_depth"  # gauge: finalised queue
    STASH_DEPTH = "stash_depth"                # gauge: ordering stashes
    VIEW_CHANGES = "view_changes"              # counter (recovery lane)
    CATCHUPS = "catchups"                      # counter (recovery lane)
    ORDERED_REQUESTS = "ordered_requests"      # counter
    E2E_DROPPED = "e2e_dropped"                # counter: intake-ts map full

    # ---- pipeline runtime (runtime/pipeline.py). Stage histograms are
    # wall-clock per job on the worker side; queue_wait is the
    # enqueue→prod-delivery handoff latency (the budget's `queue_wait`
    # stage — handoff cost stays attributable instead of smearing into
    # 3PC); depth gauges are the backpressure signals the admission
    # ladder folds into BACKLOG_DEPTH.
    PIPELINE_QUEUE_DEPTH = "pipeline_queue_depth"        # gauge: jobs
    PIPELINE_EXEC_QUEUE_DEPTH = "pipeline_exec_queue_depth"  # gauge
    PIPELINE_PARSE_MS = "pipeline_parse_ms"              # histogram
    PIPELINE_PRESCREEN_MS = "pipeline_prescreen_ms"      # histogram
    PIPELINE_QUEUE_WAIT_MS = "pipeline_queue_wait_ms"    # histogram


# ---- device seams (lane accounting). One constant per bucket-padding
# dispatch half; the seam string becomes the `seam` label in snapshots
# and Prometheus exposition.
SEAM_MESH = "mesh"                    # ops/mesh.py shard padding
SEAM_ED25519 = "ed25519"              # verify_batch_async pow2 bucket
SEAM_HUB = "hub_ed25519"              # CoalescingVerifierHub launches
SEAM_DAEMON = "daemon_ed25519"        # verify daemon fixed buckets
SEAM_SHA256 = "sha256"                # SHA-256 block buckets
SEAM_SHA3 = "sha3"                    # SHA3 block buckets
SEAM_TRIE = "trie_jax"                # MPT level batch-axis buckets
SEAM_MERKLE_APPEND = "merkle_append"  # per-level append buckets
SEAM_MERKLE_BUILD = "merkle_build"    # pow2 capacity builds
SEAM_BLS = "bls_jobs"                 # BLS job-axis identity padding
SEAM_BLS_PAIR = "bls_pairing"         # pairing verify (job, pair) buckets
SEAM_BLS_MSM = "bls_msm"              # windowed MSM point-axis buckets


def _cfg(name: str, default):
    from plenum_tpu.common.config import Config
    return getattr(Config, name, default)


# ------------------------------------------------------------ histogram

# shared bucket-edge arrays, one per (lo, octaves, sub) configuration
_EDGE_CACHE: Dict[Tuple[float, int, int], np.ndarray] = {}


def _edges(lo: float, octaves: int, sub: int) -> np.ndarray:
    """Bucket LOWER edges: edge[0]=0 (underflow), then lo·2^o·(1+s/sub)
    for o in [0, octaves), s in [0, sub), then the overflow bucket at
    lo·2^octaves. len == n_buckets == 2 + octaves·sub."""
    key = (lo, octaves, sub)
    cached = _EDGE_CACHE.get(key)
    if cached is None:
        scale = lo * np.power(2.0, np.arange(octaves))[:, None]
        lin = 1.0 + np.arange(sub)[None, :] / sub
        body = (scale * lin).reshape(-1)
        cached = _EDGE_CACHE[key] = np.concatenate(
            [[0.0], body, [lo * 2.0 ** octaves]])
    return cached


class LogLinearHistogram:
    """Preallocated log-linear histogram with bounded-relative-error
    quantiles.

    Buckets: one underflow bucket below ``lo``, then ``sub`` linear
    sub-buckets per power-of-two octave for ``octaves`` octaves, then
    one overflow bucket. A value v >= lo lands in a bucket whose width
    relative to its lower edge is at most 1/sub, so any quantile
    estimate is within a factor (1 + 1/sub) of the true order
    statistic. Defaults (lo=1 µs in ms units, 30 octaves, 16
    sub-buckets) cover 1 µs .. ~18 min at <= 6.25% relative error in
    482 int64 buckets (~4 KB).

    ``merge`` adds count arrays — pool-wide quantiles from per-node
    histograms are exactly the quantiles of recording into one hub.
    """

    __slots__ = ("lo", "octaves", "sub", "counts", "count", "total",
                 "vmin", "vmax", "_lock")

    def __init__(self, lo: float = None, octaves: int = None,
                 sub: int = None):
        self.lo = float(_cfg("TELEMETRY_HIST_LO_MS", 0.001)
                        if lo is None else lo)
        self.octaves = int(_cfg("TELEMETRY_HIST_OCTAVES", 30)
                           if octaves is None else octaves)
        self.sub = int(_cfg("TELEMETRY_HIST_SUB_BUCKETS", 16)
                       if sub is None else sub)
        self.counts = np.zeros(2 + self.octaves * self.sub,
                               dtype=np.int64)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._lock = threading.Lock()

    def _index(self, value: float) -> int:
        r = value / self.lo
        if r < 1.0:
            return 0
        # r = m · 2^e with m in [0.5, 1) → octave e-1, linear position
        # within the octave = 2m - 1 in [0, 1)
        m, e = math.frexp(r)
        octave = e - 1
        if octave >= self.octaves:
            return len(self.counts) - 1
        return 1 + octave * self.sub + int((m + m - 1.0) * self.sub)

    def record(self, value: float) -> None:
        value = float(value)
        if value < 0.0 or value != value:      # negative / NaN: drop
            return
        idx = self._index(value)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.total += value
            if self.vmin is None or value < self.vmin:
                self.vmin = value
            if self.vmax is None or value > self.vmax:
                self.vmax = value

    def quantile(self, q: float) -> Optional[float]:
        """q in [0, 1] → bucket representative (midpoint) holding the
        nearest-rank order statistic; None when empty."""
        with self._lock:
            n = self.count
            if n == 0:
                return None
            rank = min(n, max(1, int(math.ceil(q * n))))
            cum = np.cumsum(self.counts)
        idx = int(np.searchsorted(cum, rank))
        edges = _edges(self.lo, self.octaves, self.sub)
        lo_edge = edges[idx]
        hi_edge = edges[idx + 1] if idx + 1 < len(edges) else edges[idx]
        # clamp into the observed range: a single-bucket distribution
        # must not report a quantile outside [min, max]
        mid = (lo_edge + hi_edge) / 2.0
        if self.vmax is not None:
            mid = min(mid, self.vmax)
        if self.vmin is not None:
            mid = max(mid, self.vmin)
        return float(mid)

    def merge(self, other: "LogLinearHistogram") -> None:
        assert (self.lo, self.octaves, self.sub) == \
            (other.lo, other.octaves, other.sub), \
            "histogram configs must match to merge"
        with other._lock:
            counts = other.counts.copy()
            count, total = other.count, other.total
            vmin, vmax = other.vmin, other.vmax
        with self._lock:
            self.counts += counts
            self.count += count
            self.total += total
            if vmin is not None:
                self.vmin = vmin if self.vmin is None \
                    else min(self.vmin, vmin)
            if vmax is not None:
                self.vmax = vmax if self.vmax is None \
                    else max(self.vmax, vmax)

    def snapshot(self, buckets: bool = False) -> dict:
        with self._lock:
            out = {
                "count": self.count,
                "sum": round(self.total, 6),
                "min": self.vmin,
                "max": self.vmax,
            }
            if buckets:
                nz = np.nonzero(self.counts)[0]
                out["buckets"] = {int(i): int(self.counts[i]) for i in nz}
                out["lo"] = self.lo
                out["sub"] = self.sub
                out["octaves"] = self.octaves
        for label, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99),
                         ("p999", 0.999)):
            v = self.quantile(q)
            out[label] = round(v, 6) if v is not None else None
        return out


# ----------------------------------------------------------- seam stats

class _SeamStats:
    """Device-efficiency accounting for one dispatch seam."""

    __slots__ = ("launches", "useful_rows", "lane_rows", "shapes",
                 "compile_events", "last_launch_t", "idle_gap",
                 "roundtrip", "first_call")

    def __init__(self):
        self.launches = 0
        self.useful_rows = 0
        self.lane_rows = 0
        self.shapes = set()      # distinct bucket shapes seen (capped)
        self.compile_events = 0
        self.last_launch_t: Optional[float] = None
        self.idle_gap = LogLinearHistogram()
        self.roundtrip = LogLinearHistogram()
        self.first_call = LogLinearHistogram()

    def merge(self, other: "_SeamStats") -> None:
        self.launches += other.launches
        self.useful_rows += other.useful_rows
        self.lane_rows += other.lane_rows
        self.shapes |= other.shapes
        self.compile_events += other.compile_events
        if other.last_launch_t is not None:
            self.last_launch_t = other.last_launch_t \
                if self.last_launch_t is None \
                else max(self.last_launch_t, other.last_launch_t)
        self.idle_gap.merge(other.idle_gap)
        self.roundtrip.merge(other.roundtrip)
        self.first_call.merge(other.first_call)

    def snapshot(self) -> dict:
        occ = (self.useful_rows / self.lane_rows) if self.lane_rows \
            else None
        return {
            "launches": self.launches,
            "useful_rows": self.useful_rows,
            "lane_rows": self.lane_rows,
            "lane_occupancy": round(occ, 4) if occ is not None else None,
            "shapes": len(self.shapes),
            "compile_events": self.compile_events,
            "roundtrip_ms": self.roundtrip.snapshot(),
            "idle_gap_ms": self.idle_gap.snapshot(),
            "first_call_ms": self.first_call.snapshot(),
        }


# ------------------------------------------------------------- the hub

class _TimerCtx:
    __slots__ = ("_hub", "_name", "_t0")

    def __init__(self, hub: "TelemetryHub", name: str):
        self._hub = hub
        self._name = name

    def __enter__(self):
        self._t0 = self._hub._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._hub.observe(self._name,
                          (self._hub._clock() - self._t0) * 1e3)
        return False


class TelemetryHub:
    """Per-node (or per-process, for the shared device seams) telemetry
    recorder: counters, gauges, log-linear histograms and per-seam
    device-efficiency accounting, mergeable across nodes."""

    enabled = True

    def __init__(self, name: str = "", clock=time.perf_counter):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Tuple[float, float]] = {}   # name→(t, v)
        self._hists: Dict[str, LogLinearHistogram] = {}
        # family → label → histogram (observe_labeled); label count
        # capped per family — overflow folds into "_other" so a
        # hostile/huge label set can never grow the registry unbounded
        self._labeled: Dict[str, Dict[str, LogLinearHistogram]] = {}
        self._seams: Dict[str, _SeamStats] = {}
        history = int(_cfg("TELEMETRY_FLUSH_HISTORY", 512))
        self._flush_history: deque = deque(maxlen=history)

    # ---------------------------------------------------------- recording

    def clock(self) -> float:
        return self._clock()

    def _hist(self, name: str) -> LogLinearHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, LogLinearHistogram())
        return h

    def observe(self, name: str, value_ms: float) -> None:
        """Record one histogram observation (milliseconds for *_MS
        metrics)."""
        self._hist(name).record(value_ms)

    def timer(self, name: str) -> _TimerCtx:
        """Context manager observing the block's wall duration (ms)."""
        return _TimerCtx(self, name)

    def observe_labeled(self, name: str, label: str,
                        value_ms: float) -> None:
        """Record into the labeled-histogram family ``name`` under
        ``label`` (e.g. a peer node name). The FAMILY name must be a
        TM registry constant (PT009: dynamic names at record sites are
        unbounded cardinality); the label is a value, capped per family
        at TELEMETRY_LABELS_MAX distinct entries — later labels fold
        into "_other" instead of growing the registry."""
        fam = self._labeled.get(name)
        if fam is None:
            with self._lock:
                fam = self._labeled.setdefault(name, {})
        h = fam.get(label)
        if h is None:
            with self._lock:
                if label not in fam and \
                        len(fam) >= int(_cfg("TELEMETRY_LABELS_MAX", 64)):
                    label = "_other"
                h = fam.setdefault(label, LogLinearHistogram())
        h.record(value_ms)

    def labeled(self, name: str) -> dict:
        """The live label → histogram map for one family ({} if never
        recorded). Read-only for callers, like ``histogram``."""
        return self._labeled.get(name) or {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        sample = (self._clock(), float(value))
        with self._lock:
            self._gauges[name] = sample

    def _seam(self, seam: str) -> _SeamStats:
        s = self._seams.get(seam)
        if s is None:
            with self._lock:
                s = self._seams.setdefault(seam, _SeamStats())
        return s

    def record_launch(self, seam: str, useful: int, lanes: int,
                      shape=None) -> bool:
        """Account one device launch at a bucket-padding seam:
        ``useful`` real rows out of ``lanes`` launched lanes (padding =
        lanes - useful). Records the inter-dispatch idle gap, and when
        ``shape`` (the compile-relevant bucket shape) is new for this
        seam, counts a compile event. → True iff the shape was new (the
        caller can route its round-trip measurement to the first-call
        histogram)."""
        s = self._seam(seam)
        now = self._clock()
        new_shape = False
        with self._lock:
            s.launches += 1
            s.useful_rows += int(useful)
            s.lane_rows += int(lanes)
            if s.last_launch_t is not None:
                gap = (now - s.last_launch_t) * 1e3
            else:
                gap = None
            s.last_launch_t = now
            if shape is not None and shape not in s.shapes:
                new_shape = True
                s.compile_events += 1
                if len(s.shapes) < int(_cfg("TELEMETRY_SHAPE_CAP", 4096)):
                    s.shapes.add(shape)
        if gap is not None:
            s.idle_gap.record(gap)
        return new_shape

    def record_roundtrip(self, seam: str, ms: float,
                         first_call: bool = False) -> None:
        """Record one dispatch→collect round trip for a seam; with
        ``first_call`` (a launch whose bucket shape was new) the
        latency also lands in the seam's first-call histogram — the
        compile cost trajectory."""
        s = self._seam(seam)
        s.roundtrip.record(ms)
        if first_call:
            s.first_call.record(ms)

    # ------------------------------------------------------------ reading

    def merge(self, other: "TelemetryHub") -> "TelemetryHub":
        """Fold another hub's state into this one (pool-wide
        aggregation): counters and histograms add, gauges keep the
        newest sample, seams add. → self."""
        if not getattr(other, "enabled", False):
            return self
        with other._lock:
            counters = dict(other._counters)
            gauges = dict(other._gauges)
            hists = list(other._hists.items())
            labeled = [(name, list(fam.items()))
                       for name, fam in other._labeled.items()]
            seams = list(other._seams.items())
        for name, n in counters.items():
            self.count(name, n)
        with self._lock:
            for name, (t, v) in gauges.items():
                cur = self._gauges.get(name)
                if cur is None or t >= cur[0]:
                    self._gauges[name] = (t, v)
        for name, hist in hists:
            self._hist(name).merge(hist)
        for name, fam in labeled:
            with self._lock:
                mine = self._labeled.setdefault(name, {})
                # merge is aggregation-time: peers' label sets are
                # already capped at their record sites, so no re-cap
                for label, _h in fam:
                    mine.setdefault(label, LogLinearHistogram())
            for label, hist in fam:
                mine[label].merge(hist)
        for seam, stats in seams:
            self._seam(seam).merge(stats)
        return self

    def gauge_sample(self, name: str):
        """The (timestamp, value) sample of a gauge, or None if it was
        never set — read seam for live pressure consumers (the gateway
        admission ladder) that must not pay a full snapshot per tick."""
        with self._lock:
            return self._gauges.get(name)

    def histogram(self, name: str):
        """The live histogram recorded under ``name`` (None if never
        recorded). Read-only for callers: mergers fold it into their
        own scratch histogram (LogLinearHistogram.merge is add-only)."""
        return self._hists.get(name)

    def snapshot(self, buckets: bool = False) -> dict:
        """Node-local state dump. With ``buckets`` the histograms carry
        their sparse bucket arrays (what Prometheus exposition needs)."""
        with self._lock:
            # copy the registries under the lock: a concurrent first
            # record of a new name must not resize a dict mid-iteration
            # (the seam hub is recorded into from the verify-daemon
            # worker while validator info snapshots it)
            counters = dict(self._counters)
            gauges = {k: v for k, (_t, v) in self._gauges.items()}
            hists = sorted(self._hists.items())
            labeled = sorted((name, sorted(fam.items()))
                             for name, fam in self._labeled.items())
            seams = sorted(self._seams.items())
        return {
            "node": self.name,
            "enabled": True,
            "t": self._clock(),
            "counters": counters,
            "gauges": gauges,
            "histograms": {name: h.snapshot(buckets=buckets)
                           for name, h in hists},
            "labeled": {name: {label: h.snapshot(buckets=buckets)
                               for label, h in fam}
                        for name, fam in labeled},
            "seams": {seam: s.snapshot() for seam, s in seams},
        }

    def flush(self) -> dict:
        """Take a timestamped sample of the headline series (counter
        totals, gauges, histogram p50/p99, per-seam occupancy) into the
        bounded flush history — the time axis the Perfetto exporter
        renders as counter tracks. → the sample."""
        t = self._clock()
        sample: Dict[str, float] = {}
        with self._lock:
            for name, n in self._counters.items():
                sample[name] = n
            for name, (_t, v) in self._gauges.items():
                sample[name] = v
            hists = sorted(self._hists.items())
            labeled = sorted((name, sorted(fam.items()))
                             for name, fam in self._labeled.items())
            seams = sorted(self._seams.items())
        for name, fam in labeled:
            for label, h in fam:
                p99 = h.quantile(0.99)
                if p99 is not None:
                    sample[name + "." + label + ".p99"] = round(p99, 4)
        for name, h in hists:
            p50, p99 = h.quantile(0.50), h.quantile(0.99)
            if p50 is not None:
                sample[name + ".p50"] = round(p50, 4)
            if p99 is not None:
                sample[name + ".p99"] = round(p99, 4)
        for seam, s in seams:
            if s.lane_rows:
                sample["lane_occupancy." + seam] = round(
                    s.useful_rows / s.lane_rows, 4)
        self._flush_history.append((t, sample))
        return sample

    def flush_history(self):
        return list(self._flush_history)

    # --------------------------------------------------------- exposition

    def to_prometheus(self) -> str:
        return prometheus_text(self.snapshot(buckets=True))

    def write_prometheus(self, path: str) -> str:
        """Atomic write of the Prometheus text exposition; → path."""
        text = self.to_prometheus()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        return path


class NullTelemetryHub:
    """The disabled default: every record call is a no-op attribute
    call (Config.TELEMETRY_ENABLED=False restores the pre-telemetry
    cost exactly)."""

    __slots__ = ("name",)
    enabled = False

    def __init__(self, name: str = ""):
        self.name = name

    def clock(self) -> float:
        return 0.0

    def observe(self, name, value_ms) -> None:
        pass

    def observe_labeled(self, name, label, value_ms) -> None:
        pass

    def labeled(self, name) -> dict:
        return {}

    def timer(self, name):
        return _NULL_TIMER

    def count(self, name, n=1) -> None:
        pass

    def gauge(self, name, value) -> None:
        pass

    def record_launch(self, seam, useful, lanes, shape=None) -> bool:
        return False

    def record_roundtrip(self, seam, ms, first_call=False) -> None:
        pass

    def gauge_sample(self, name):
        return None

    def histogram(self, name):
        return None

    def merge(self, other):
        return self

    def snapshot(self, buckets: bool = False) -> dict:
        return {"node": self.name, "enabled": False}

    def flush(self) -> dict:
        return {}

    def flush_history(self):
        return []


class _NullTimerCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_TIMER = _NullTimerCtx()


# -------------------------------------------------- process-wide seam hub

# The device seams (mesh, kernels, shared verifier hub/daemon) are
# process-wide resources shared by every co-resident node — exactly
# like the mesh tracer attach, their lane accounting lands in ONE
# process hub rather than an arbitrary node's. Pool-wide reports merge
# it with the per-node hubs.
_SEAM_HUB: Optional[object] = None
_SEAM_HUB_LOCK = threading.Lock()


def get_seam_hub():
    """The process-wide hub the ops/ dispatch seams record into.
    Created lazily from the Config class default (TELEMETRY_ENABLED
    False → a NullTelemetryHub, zero cost)."""
    global _SEAM_HUB
    hub = _SEAM_HUB
    if hub is None:
        with _SEAM_HUB_LOCK:
            if _SEAM_HUB is None:
                if _cfg("TELEMETRY_ENABLED", True):
                    _SEAM_HUB = TelemetryHub(name="device-seams")
                else:
                    _SEAM_HUB = NullTelemetryHub(name="device-seams")
            hub = _SEAM_HUB
    return hub


def set_seam_hub(hub):
    """Swap the process seam hub (tests / bench configs isolate their
    lane accounting); → the previous hub."""
    global _SEAM_HUB
    with _SEAM_HUB_LOCK:
        prev = _SEAM_HUB
        _SEAM_HUB = hub
    return prev


# --------------------------------------------------- prometheus rendering

def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "plenum_" + "".join(out)


def prometheus_text(snapshot: dict) -> str:
    """Render a hub snapshot (``snapshot(buckets=True)``) as Prometheus
    text exposition: counters as ``counter``, gauges as ``gauge``,
    histograms as native prom histograms (cumulative ``le`` buckets at
    the log-linear upper edges, sparse — only edges with occupancy),
    per-seam lane accounting as labeled counters/gauges. Deterministic
    output for a given snapshot."""
    node = snapshot.get("node", "")
    label = '{node="%s"}' % node if node else ""

    def seam_label(seam: str) -> str:
        if node:
            return '{node="%s",seam="%s"}' % (node, seam)
        return '{seam="%s"}' % seam

    lines = []
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        pn = _prom_name(name) + "_total"
        lines.append("# TYPE %s counter" % pn)
        lines.append("%s%s %d" % (pn, label, value))
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        pn = _prom_name(name)
        lines.append("# TYPE %s gauge" % pn)
        lines.append("%s%s %g" % (pn, label, value))
    for name, h in sorted((snapshot.get("histograms") or {}).items()):
        pn = _prom_name(name)
        lines.append("# TYPE %s histogram" % pn)
        # JSON round trips stringify bucket indices (telemetry_stats
        # --prom on a snapshot file) — normalize before use
        buckets = {int(k): v for k, v in (h.get("buckets") or {}).items()}
        if buckets:
            edges = _edges(h["lo"], h["octaves"], h["sub"])
            cum = 0
            for idx in sorted(buckets):
                cum += buckets[idx]
                if idx + 1 >= len(edges):
                    # overflow bucket: covered by the single +Inf line
                    # below — emitting it here too would duplicate the
                    # le="+Inf" series and invalidate the exposition
                    continue
                lines.append('%s_bucket{%sle="%g"} %d' % (
                    pn, ('node="%s",' % node) if node else "",
                    edges[idx + 1], cum))
        lines.append('%s_bucket{%sle="+Inf"} %d' % (
            pn, ('node="%s",' % node) if node else "", h.get("count", 0)))
        lines.append("%s_sum%s %g" % (pn, label, h.get("sum") or 0.0))
        lines.append("%s_count%s %d" % (pn, label, h.get("count", 0)))
    for name, fam in sorted((snapshot.get("labeled") or {}).items()):
        pn = _prom_name(name)
        lines.append("# TYPE %s summary" % pn)
        for lab, h in sorted(fam.items()):
            ll = ('{node="%s",label="%s"}' % (node, lab)) if node \
                else '{label="%s"}' % lab
            for q in ("p50", "p99"):
                if h.get(q) is not None:
                    lines.append('%s%s %g' % (
                        pn + "_" + q, ll, h[q]))
            lines.append("%s_sum%s %g" % (pn, ll, h.get("sum") or 0.0))
            lines.append("%s_count%s %d" % (pn, ll, h.get("count", 0)))
    for seam, s in sorted((snapshot.get("seams") or {}).items()):
        sl = seam_label(seam)
        lines.append("plenum_lane_useful_rows_total%s %d"
                     % (sl, s.get("useful_rows", 0)))
        lines.append("plenum_lane_rows_total%s %d"
                     % (sl, s.get("lane_rows", 0)))
        occ = s.get("lane_occupancy")
        if occ is not None:
            lines.append("plenum_lane_occupancy%s %g" % (sl, occ))
        lines.append("plenum_seam_launches_total%s %d"
                     % (sl, s.get("launches", 0)))
        lines.append("plenum_seam_compile_events_total%s %d"
                     % (sl, s.get("compile_events", 0)))
        rt = s.get("roundtrip_ms") or {}
        for q in ("p50", "p99"):
            if rt.get(q) is not None:
                lines.append("plenum_seam_roundtrip_ms_%s%s %g"
                             % (q, sl, rt[q]))
    return "\n".join(lines) + "\n"


def merged_snapshot(hubs, name: str = "pool", buckets: bool = False
                    ) -> dict:
    """Merge any iterable of hubs (per-node + the process seam hub)
    into one pool-wide snapshot."""
    merged = TelemetryHub(name=name)
    for hub in hubs:
        if hub is not None and getattr(hub, "enabled", False):
            merged.merge(hub)
    return merged.snapshot(buckets=buckets)
