"""Chrome trace-event export — Perfetto-loadable pool timelines.

Converts any set of Tracer ring buffers (one per node, plus standalone
tracers like the verify daemon's) into the Trace Event Format that
chrome://tracing and https://ui.perfetto.dev load directly:

* one "pid" row per tracer (the node name, via process_name metadata),
* one "tid" track per span category within a node (thread_name
  metadata) — intake / propagate / 3pc / execute / device / bls /
  reply render as parallel lanes per node,
* complete events ("X") for spans, instants ("i") for quorum markers,
  counter events ("C") for queue depths and batch sizes,
* every event's args carry its correlation key ("key": request digest
  or "viewNo:ppSeqNo"), so Perfetto's search/flow UI groups one batch's
  whole lifecycle across all nodes,
* flow events ("s"/"f") pairing each stamped envelope's ``wire_send``
  with every ``wire_recv`` it produced — Perfetto draws the arrow from
  the sender's flush to each receiver's parse, which is what makes a
  cross-node journey READABLE on the timeline. The flow id is the
  stamp identity "origin:flushSeq" (the receive instants' key), so
  send and receives bind with no extra bookkeeping.

Timestamps are the tracers' shared perf_counter clock in microseconds;
within one process (the sim pool, the e2e harness) that makes the
merged timeline causally consistent with no alignment step. Output is
deterministic for a given set of buffers: pids follow tracer order,
tids follow first-appearance order, and the timeline is sorted by
(ts, pid, tid, name).
"""
from __future__ import annotations

import json
from typing import Iterable, List, Optional


def trace_events(tracers: Iterable, telemetry: Iterable = ()) -> List[dict]:
    """→ Trace Event Format event list (metadata first, then the
    time-sorted merged timeline). ``telemetry`` hubs
    (observability/telemetry.py) contribute their flush-history samples
    as counter tracks — histogram p50/p99, pool-health gauges and
    per-seam lane occupancy line up on the same perf_counter time axis
    as the spans, one "telemetry" lane per hub."""
    meta: List[dict] = []
    timeline: List[dict] = []
    pid_of: dict = {}
    for tracer in tracers:
        if tracer is None:
            continue
        recs = tracer.spans()
        if not recs:
            continue
        pname = tracer.name or "node"
        pid = pid_of.get(pname)
        if pid is None:
            pid = pid_of[pname] = len(pid_of) + 1
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": pname}})
        tids: dict = {}
        for kind, name, cat, t0, t1, key, args in recs:
            track = cat or "main"
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                meta.append({"name": "thread_name", "ph": "M",
                             "pid": pid, "tid": tid,
                             "args": {"name": track}})
            ts = int(round(t0 * 1e6))
            payload = dict(args) if args else {}
            if key is not None:
                payload["key"] = key
            if kind == "X":
                timeline.append({
                    "name": name, "cat": track, "ph": "X", "pid": pid,
                    "tid": tid, "ts": ts,
                    "dur": max(0, int(round((t1 - t0) * 1e6))),
                    "args": payload})
            elif kind == "i":
                timeline.append({
                    "name": name, "cat": track, "ph": "i", "pid": pid,
                    "tid": tid, "ts": ts, "s": "t", "args": payload})
                # journey flow arrows: one "s" per stamped envelope
                # send, one "f" per receive; both share the stamp
                # identity as the flow id (a broadcast send fans out
                # to one arrow per receiver)
                if name == "wire_send" and key is not None:
                    timeline.append({
                        "name": "wire", "cat": track, "ph": "s",
                        "id": "%s:%s" % (pname, key), "pid": pid,
                        "tid": tid, "ts": ts, "args": {}})
                elif name == "wire_recv" and key is not None:
                    timeline.append({
                        "name": "wire", "cat": track, "ph": "f",
                        "bp": "e", "id": key, "pid": pid,
                        "tid": tid, "ts": ts, "args": {}})
            else:  # "C"
                timeline.append({
                    "name": name, "ph": "C", "pid": pid, "tid": tid,
                    "ts": ts, "args": payload})
    for hub in telemetry or ():
        if hub is None or not getattr(hub, "enabled", False):
            continue
        history = hub.flush_history()
        if not history:
            continue
        pname = hub.name or "telemetry"
        pid = pid_of.get(pname)
        if pid is None:
            pid = pid_of[pname] = len(pid_of) + 1
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": pname}})
        # one dedicated counter lane per hub, after any span tracks the
        # same pid already claimed
        tid = 1000
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": "telemetry"}})
        for t, sample in history:
            ts = int(round(t * 1e6))
            for name in sorted(sample):
                timeline.append({
                    "name": name, "ph": "C", "pid": pid, "tid": tid,
                    "ts": ts, "args": {name: sample[name]}})
    timeline.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"]))
    return meta + timeline


def chrome_trace(tracers: Iterable, telemetry: Iterable = ()) -> dict:
    """→ the full JSON-object trace document."""
    return {"traceEvents": trace_events(tracers, telemetry=telemetry),
            "displayTimeUnit": "ms"}


def export_chrome_trace(tracers: Iterable, path: str,
                        telemetry: Iterable = ()) -> str:
    """Write the merged timeline to `path`; → path."""
    doc = chrome_trace(tracers, telemetry=telemetry)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def pool_telemetry(nodes: Iterable) -> List:
    """Collect every node's TelemetryHub (skipping nodes without one or
    with telemetry off) — the counter-track set for a pool timeline and
    the merge set for pool-wide snapshots."""
    out = []
    for node in nodes:
        hub = getattr(node, "telemetry", None)
        if hub is not None and getattr(hub, "enabled", False):
            out.append(hub)
    return out


def pool_tracers(nodes: Iterable) -> List:
    """Collect every node's tracer (skipping nodes without one) — the
    merge set for a pool-wide timeline."""
    out = []
    for node in nodes:
        tracer = getattr(node, "tracer", None)
        if tracer is not None:
            out.append(tracer)
    return out


def summarize(doc: dict) -> dict:
    """Compact summary of a trace document (the `trace_view` CLI's
    validation/reporting half): event counts per phase kind, span-name
    histogram per node, counter-track value ranges, wall span of the
    timeline."""
    events = doc.get("traceEvents", [])
    pid_names = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    by_ph: dict = {}
    by_node: dict = {}
    counters: dict = {}
    t_min: Optional[int] = None
    t_max: Optional[int] = None
    for e in events:
        ph = e.get("ph")
        by_ph[ph] = by_ph.get(ph, 0) + 1
        if ph == "M":
            continue
        ts = e.get("ts", 0)
        end = ts + e.get("dur", 0)
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = end if t_max is None else max(t_max, end)
        node = pid_names.get(e["pid"], str(e["pid"]))
        names = by_node.setdefault(node, {})
        names[e["name"]] = names.get(e["name"], 0) + 1
        if ph == "C":
            # counter tracks: keep the value envelope per series so the
            # file-mode summary reports them instead of dropping them
            for v in (e.get("args") or {}).values():
                if not isinstance(v, (int, float)):
                    continue
                cur = counters.get(e["name"])
                if cur is None:
                    counters[e["name"]] = {
                        "points": 1, "min": v, "max": v, "last": v}
                else:
                    cur["points"] += 1
                    cur["min"] = min(cur["min"], v)
                    cur["max"] = max(cur["max"], v)
                    cur["last"] = v
    return {
        "events": len(events),
        "by_ph": by_ph,
        "nodes": sorted(by_node),
        "span_counts": by_node,
        "counters": counters,
        "wall_us": (t_max - t_min) if t_min is not None else 0,
    }
