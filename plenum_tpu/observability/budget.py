"""Per-stage host-millisecond budget from flight-recorder spans.

The columnar 3PC refactor's contract is attributability: every
host-side millisecond on the ordering money path belongs to a named
stage, so a throughput regression shows up as ONE stage's budget
moving, not a vague end-to-end slowdown. This module turns a set of
recorded spans — either live ``Tracer`` ring buffers or an exported
Chrome trace document — into ``host-ms per ordered request`` per
stage:

* ``intake``    — client batch auth dispatch/conclude + read batches
* ``propagate`` — PROPAGATE flush + quorum bookkeeping
* ``queue_wait`` — pipeline handoff: prod-thread time blocked on a
                  parse worker at the drain (runtime/pipeline.py)
* ``3pc``       — PRE-PREPARE build/process, columnar prepare/commit
                  intake, ordering, the per-tick vote flush
* ``dispatch_wait`` — device seams (fused per-batch window, verifier
                  hub flush/collect, BLS aggregation)
* ``execute``   — batch apply/commit MINUS the device window nested
                  inside it (exclusive time: nested spans are charged
                  to their own stage exactly once)
* ``reply``     — reply construction + audit paths

Span time is EXCLUSIVE: a ``fused_dispatch`` nested inside
``batch_apply`` counts toward ``dispatch_wait``, and only the
remaining apply time counts toward ``execute`` — stages sum to real
host time, double counting nothing. Ordered-request volume is taken
from the master executor's ``batch_apply`` spans (``batch_size``
arg), the one span family that fires exactly once per applied batch
per node.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from plenum_tpu.observability.telemetry import TM as _TM

# stage order is the money-path order; reports preserve it
STAGES = ("intake", "propagate", "serialize", "parse", "queue_wait",
          "3pc", "dispatch_wait", "execute", "reply")

# named sub-stages of the execute budget line (conflict-lane executor,
# server/executor.py): plan+prefetch / per-request validate-apply /
# merged hash resolution. They carry the execute category, so their
# exclusive time already lands in the execute stage — the sub-stage
# report says WHICH of the three owns it. (The device work nested
# inside hash_resolve keeps charging dispatch_wait, exactly like the
# fused window always has.)
EXECUTE_SUBSTAGES = ("exec_validate", "lane_apply", "hash_resolve")

# span names whose category alone would misfile them: the intake auth
# seams are device dispatches, but they are the INTAKE stage's cost;
# the wire pack/parse spans sit inside 3PC/propagate flush handlers but
# are the SERIALIZE/PARSE stages' cost (the flat-wire A/B reads the
# before/after host-ms off these two rows instead of inferring it from
# an end-to-end delta)
_INTAKE_NAMES = frozenset({"auth_dispatch", "auth_conclude",
                           "read_batch"})
_NAME_TO_STAGE = {
    "wire_pack": "serialize",
    "wire_parse": "parse",
    # pipeline handoff: prod-thread time spent blocked on a parse
    # worker (runtime/pipeline.py drain). Its own stage so handoff
    # latency is attributable instead of smearing into the consuming
    # 3PC stage — a mis-sized queue shows up as THIS row moving.
    "queue_wait": "queue_wait",
}
_CAT_TO_STAGE = {
    "intake": "intake",
    "propagate": "propagate",
    "3pc": "3pc",
    "device": "dispatch_wait",
    "bls": "dispatch_wait",
    "execute": "execute",
    "reply": "reply",
}


def stage_of(name: str, cat: str) -> Optional[str]:
    """Stage for one span; None = unbudgeted (recovery, counters)."""
    if name in _INTAKE_NAMES:
        return "intake"
    stage = _NAME_TO_STAGE.get(name)
    if stage is not None:
        return stage
    return _CAT_TO_STAGE.get(cat)


def _exclusive_ms(spans: List[Tuple[float, float, str, str]]
                  ) -> Tuple[Dict[str, float], Dict[str, float]]:
    """(t0, t1, stage, name) spans from ONE single-threaded recorder →
    (per-stage, per-execute-sub-stage) EXCLUSIVE milliseconds. Nested
    spans (device windows inside an apply, batch intakes inside a
    flush) are charged to their own stage and subtracted from the
    enclosing span's stage; the named executor sub-stages additionally
    accumulate their own exclusive time so the execute line splits
    into validate / lane-apply / hash-resolve populations."""
    out: Dict[str, float] = {s: 0.0 for s in STAGES}
    subs: Dict[str, float] = {s: 0.0 for s in EXECUTE_SUBSTAGES}
    # parents sort before their children; among equal starts the longer
    # span is the parent
    spans = sorted(spans, key=lambda s: (s[0], -s[1]))
    stack: List[List] = []   # [t0, t1, stage, name, child_time]
    def _close(entry):
        t0, t1, stage, name, child = entry
        excl = max(0.0, (t1 - t0) - child) * 1e3
        if stage is not None:
            out[stage] += excl
        if name in subs:
            subs[name] += excl
        if stack:
            stack[-1][4] += t1 - t0
    for t0, t1, stage, name in spans:
        while stack and t0 >= stack[-1][1]:
            _close(stack.pop())
        stack.append([t0, t1, stage, name, 0.0])
    while stack:
        _close(stack.pop())
    return out, subs


def budget_from_tracers(tracers: Iterable) -> dict:
    """Live ``Tracer`` buffers (one per node) → the budget report (see
    :func:`_report`)."""
    per_node: List[Dict[str, float]] = []
    per_node_subs: List[Dict[str, float]] = []
    ordered: List[int] = []
    for tracer in tracers:
        if tracer is None:
            continue
        spans, n_ordered = [], 0
        for kind, name, cat, t0, t1, key, args in tracer.spans():
            if kind != "X":
                continue
            spans.append((t0, t1, stage_of(name, cat), name))
            if name == "batch_apply" and args:
                n_ordered += int(args.get("batch_size", 0))
        if spans:
            stage_ms, sub_ms = _exclusive_ms(spans)
            per_node.append(stage_ms)
            per_node_subs.append(sub_ms)
            ordered.append(n_ordered)
    return _report(per_node, ordered, per_node_subs)


def budget_from_chrome(doc: dict) -> dict:
    """Exported Chrome trace document (``trace_view`` / scenario
    dumps) → the budget report. Timestamps are microseconds."""
    by_pid: Dict[int, List[Tuple[float, float, Optional[str], str]]] = {}
    ordered_by_pid: Dict[int, int] = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        pid = e.get("pid", 0)
        t0 = e.get("ts", 0) * 1e-6
        t1 = t0 + e.get("dur", 0) * 1e-6
        name = e.get("name", "")
        by_pid.setdefault(pid, []).append(
            (t0, t1, stage_of(name, e.get("cat", "")), name))
        if name == "batch_apply":
            ordered_by_pid[pid] = ordered_by_pid.get(pid, 0) + \
                int((e.get("args") or {}).get("batch_size", 0))
    per_node, per_node_subs = [], []
    for spans in by_pid.values():
        stage_ms, sub_ms = _exclusive_ms(spans)
        per_node.append(stage_ms)
        per_node_subs.append(sub_ms)
    ordered = [ordered_by_pid.get(pid, 0) for pid in by_pid]
    return _report(per_node, ordered, per_node_subs)


def _report(per_node: List[Dict[str, float]], ordered: List[int],
            per_node_subs: List[Dict[str, float]] = None) -> dict:
    """Merge per-node stage totals into the budget report:

    * ``ordered_reqs`` — requests applied (max across nodes: every
      node applies every batch, stragglers just show fewer),
    * ``stage_ms_per_node`` — average total host-ms per stage per node,
    * ``host_ms_per_ordered_req`` — per-stage average host-ms one
      ordered request costs ONE node, plus ``total``,
    * ``execute_substages`` — the execute line split into the lane
      executor's validate / lane-apply / hash-resolve populations
      (ms per ordered request; absent when nothing recorded them).
    """
    n_nodes = len(per_node)
    n_ordered = max(ordered) if ordered else 0
    totals = {s: sum(node[s] for node in per_node) for s in STAGES} \
        if per_node else {s: 0.0 for s in STAGES}
    avg = {s: totals[s] / n_nodes for s in STAGES} if n_nodes else totals
    per_req = {s: (avg[s] / n_ordered if n_ordered else 0.0)
               for s in STAGES}
    per_req["total"] = sum(per_req[s] for s in STAGES)
    report = {
        "nodes": n_nodes,
        "ordered_reqs": n_ordered,
        "stage_ms_per_node": {s: round(avg[s], 2) for s in STAGES},
        "host_ms_per_ordered_req": {
            s: round(v, 4) for s, v in per_req.items()},
    }
    if per_node_subs and n_nodes and any(
            any(v for v in subs.values()) for subs in per_node_subs):
        sub_avg = {s: sum(subs.get(s, 0.0) for subs in per_node_subs)
                   / n_nodes for s in EXECUTE_SUBSTAGES}
        report["execute_substages"] = {
            s: round(sub_avg[s] / n_ordered if n_ordered else 0.0, 4)
            for s in EXECUTE_SUBSTAGES}
    return report


# telemetry stage-latency histogram feeding each budget stage's
# measured-p99 column (observability/telemetry.py TM names): the
# budget's exclusive-ms MEANS say where host time goes; the telemetry
# p99 next to them says what the TAIL of that stage looks like — a
# stage can be cheap on average and still own the latency SLO miss
_STAGE_TELEMETRY = {
    "propagate": _TM.STAGE_PROPAGATE_MS,
    "queue_wait": _TM.PIPELINE_QUEUE_WAIT_MS,
    "3pc": _TM.STAGE_3PC_MS,
    "dispatch_wait": _TM.STAGE_DISPATCH_MS,
    "execute": _TM.STAGE_EXECUTE_MS,
    "reply": _TM.STAGE_REPLY_MS,
}


def stage_p99s(telemetry_snapshot: Optional[dict]) -> Dict[str, float]:
    """Per-budget-stage measured p99 (ms) out of a telemetry snapshot
    (hub.snapshot() / the validator-info Telemetry section); stages
    without a telemetry histogram are absent."""
    if not telemetry_snapshot:
        return {}
    hists = telemetry_snapshot.get("histograms") or {}
    out: Dict[str, float] = {}
    for stage, metric in _STAGE_TELEMETRY.items():
        p99 = (hists.get(metric) or {}).get("p99")
        if p99 is not None:
            out[stage] = p99
    return out


def format_table(report: dict, telemetry_snapshot: dict = None) -> str:
    """Human-readable per-stage table (the ``trace_budget`` CLI). With
    a telemetry snapshot, each stage's measured p99 latency prints next
    to its exclusive-ms mean — budget and tail read together."""
    p99s = stage_p99s(telemetry_snapshot)
    header = "%-14s %14s %18s %6s" % (
        "stage", "host-ms/node", "ms/ordered-req", "share")
    if p99s:
        header += " %12s" % "p99-ms"
    lines = [header]
    per_req = report["host_ms_per_ordered_req"]
    total = per_req.get("total") or 0.0
    substages = report.get("execute_substages") or {}
    for stage in STAGES:
        share = (per_req[stage] / total * 100.0) if total else 0.0
        line = "%-14s %14.2f %18.4f %5.1f%%" % (
            stage, report["stage_ms_per_node"][stage], per_req[stage],
            share)
        if p99s:
            line += " %12s" % (("%.3f" % p99s[stage])
                               if stage in p99s else "-")
        lines.append(line)
        if stage == "execute" and substages:
            # the conflict-lane executor's split of the execute budget
            for name in EXECUTE_SUBSTAGES:
                lines.append("  %-12s %14s %18.4f" % (
                    name.replace("exec_", ""), "",
                    substages.get(name, 0.0)))
    lines.append("%-14s %14s %18.4f" % (
        "total", "", total))
    if p99s and telemetry_snapshot:
        e2e = ((telemetry_snapshot.get("histograms") or {})
               .get(_TM.ORDERED_E2E_MS) or {})
        if e2e.get("p99") is not None:
            lines.append("ordered e2e: p50=%.3f ms  p99=%.3f ms  "
                         "(telemetry, n=%d)" % (
                             e2e.get("p50") or 0.0, e2e["p99"],
                             e2e.get("count", 0)))
    lines.append("nodes=%d ordered_reqs=%d" % (
        report["nodes"], report["ordered_reqs"]))
    return "\n".join(lines)
