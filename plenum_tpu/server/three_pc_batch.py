"""ThreePcBatch — the unit of consensus execution.

Reference: plenum/server/batch_handlers/three_pc_batch.py.
"""
from typing import List, Optional


class ThreePcBatch:
    def __init__(self, ledger_id: int, inst_id: int, view_no: int,
                 pp_seq_no: int, pp_time: int, state_root: str,
                 txn_root: str, valid_digests: List[str],
                 pp_digest: str,
                 primaries: Optional[List[str]] = None,
                 node_reg: Optional[List[str]] = None,
                 original_view_no: Optional[int] = None,
                 has_audit_txn: bool = True):
        self.ledger_id = ledger_id
        self.inst_id = inst_id
        self.view_no = view_no
        self.pp_seq_no = pp_seq_no
        self.pp_time = pp_time
        self.state_root = state_root
        self.txn_root = txn_root
        self.valid_digests = list(valid_digests)
        self.pp_digest = pp_digest
        self.primaries = primaries or []
        self.node_reg = node_reg
        self.original_view_no = original_view_no \
            if original_view_no is not None else view_no
        self.has_audit_txn = has_audit_txn

    @property
    def three_pc_key(self):
        return (self.view_no, self.pp_seq_no)

    def __repr__(self):
        return "ThreePcBatch(ledger={}, 3pc=({}, {}), reqs={})".format(
            self.ledger_id, self.view_no, self.pp_seq_no,
            len(self.valid_digests))
