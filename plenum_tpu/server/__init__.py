"""Server layer: request execution pipeline, propagation, authentication,
node orchestration (reference: plenum/server/).
"""
