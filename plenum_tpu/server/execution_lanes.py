"""Deterministic execution-lane planning for ordered batches.

The conflict-lane executor (server/executor.py) partitions every
ordered 3PC batch into **execution lanes** keyed by the requests'
declared state touches (``WriteRequestHandler.touched_keys``): two
requests share a lane iff they are connected through keys where at
least one side WRITES — read-read sharing (every request in a loaded
pool reads a handful of hot author records) never serializes anything.
Requests whose handler cannot statically declare its key set (NODE
txns scan the whole pool state for alias uniqueness; TAA writes chase
digest chains through state) join one designated **serial lane** that
conservatively conflicts with every other lane.

Determinism: the plan is a pure function of the ordered batch — the
declared key sets in batch order, a union-find with
first-request-index representatives, and lane ids normalized by first
appearance. Every honest node computes the identical partition from
the identical PRE-PREPARE, so lane telemetry and scheduling decisions
are pool-comparable. The plan can never diverge *state*: the executor
applies requests in batch order regardless (docs/execution.md has the
full argument), so the lanes drive the batched read prefetch, the
merged hash resolve and the conflict accounting — a planning bug can
cost performance, never a root mismatch.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

# (ledger_id, state_key) — the coordinate every declaration speaks
LaneKey = Tuple[int, bytes]

# lane id of the designated serial lane (undeclared requests)
SERIAL_LANE = -1


class TouchedKeys:
    """One request's declared state touches: the key sets its handler
    promises to confine every ``state.get``/``state.set`` to during
    ``dynamic_validation`` + ``update_state`` (a SUPERSET is always
    safe — extra keys only make lane grouping more conservative).
    Handlers that cannot declare return None instead (serial lane)."""

    __slots__ = ("reads", "writes")

    def __init__(self, reads: Sequence[LaneKey] = (),
                 writes: Sequence[LaneKey] = ()):
        self.reads = tuple(reads)
        self.writes = tuple(writes)

    def with_reads(self, extra: Sequence[LaneKey]) -> "TouchedKeys":
        return TouchedKeys(self.reads + tuple(extra), self.writes)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "TouchedKeys(reads=%r, writes=%r)" % (self.reads,
                                                     self.writes)


class LanePlan:
    """The partition of one ordered batch into execution lanes."""

    __slots__ = ("lanes", "n_lanes", "serial_requests", "conflict_ratio",
                 "read_keys_by_ledger", "write_keys_by_ledger",
                 "lane_sizes")

    def __init__(self, lanes: List[int], n_lanes: int,
                 serial_requests: int, conflict_ratio: float,
                 read_keys_by_ledger: Dict[int, List[bytes]],
                 write_keys_by_ledger: Dict[int, List[bytes]],
                 lane_sizes: Dict[int, int]):
        self.lanes = lanes                  # per-request lane id
        self.n_lanes = n_lanes              # declared lanes + serial
        self.serial_requests = serial_requests
        self.conflict_ratio = conflict_ratio
        self.read_keys_by_ledger = read_keys_by_ledger
        self.write_keys_by_ledger = write_keys_by_ledger
        self.lane_sizes = lane_sizes        # lane id -> request count


def plan_lanes(touches: Sequence[Optional[TouchedKeys]]) -> LanePlan:
    """Partition one ordered batch (its per-request ``TouchedKeys`` in
    batch order; None = undeclared) into execution lanes.

    Union rule: all touchers of a key merge once ANY of them writes it
    — writer/writer, writer-then-reader and reader-then-writer all
    serialize (the reader must observe exactly the writes ordered
    before it); keys nobody writes never merge lanes. Undeclared
    requests take SERIAL_LANE. Pure function of its input: identical
    on every honest node."""
    n = len(touches)
    parent = list(range(n))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(i: int, j: int) -> None:
        ri, rj = find(i), find(j)
        if ri != rj:
            # smaller (earlier) index wins: representatives are stable
            # first-request indices, independent of union order
            if ri < rj:
                parent[rj] = ri
            else:
                parent[ri] = rj

    # key -> representative of its (write-involved) group
    write_groups: Dict[LaneKey, int] = {}
    # key -> reader indices seen before any writer of that key
    pending_readers: Dict[LaneKey, List[int]] = {}
    read_keys: Dict[int, Dict[bytes, None]] = {}
    write_keys: Dict[int, Dict[bytes, None]] = {}
    serial = 0
    for i, tk in enumerate(touches):
        if tk is None:
            serial += 1
            continue
        for key in tk.writes:
            grp = write_groups.get(key)
            if grp is not None:
                union(i, grp)
            else:
                for r in pending_readers.pop(key, ()):
                    union(i, r)
            write_groups[key] = find(i)
            write_keys.setdefault(key[0], {})[key[1]] = None
        for key in tk.reads:
            grp = write_groups.get(key)
            if grp is not None:
                union(i, grp)
                write_groups[key] = find(i)
            else:
                pending_readers.setdefault(key, []).append(i)
            read_keys.setdefault(key[0], {})[key[1]] = None
    # normalize lane ids by first appearance; undeclared -> SERIAL_LANE
    lane_of_root: Dict[int, int] = {}
    lanes: List[int] = []
    lane_sizes: Dict[int, int] = {}
    for i, tk in enumerate(touches):
        if tk is None:
            lane = SERIAL_LANE
        else:
            root = find(i)
            lane = lane_of_root.setdefault(root, len(lane_of_root))
        lanes.append(lane)
        lane_sizes[lane] = lane_sizes.get(lane, 0) + 1
    n_lanes = len(lane_of_root) + (1 if serial else 0)
    conflicted = serial + sum(
        size for lane, size in lane_sizes.items()
        if lane != SERIAL_LANE and size > 1)
    return LanePlan(
        lanes=lanes,
        n_lanes=n_lanes,
        serial_requests=serial,
        conflict_ratio=(conflicted / n) if n else 0.0,
        read_keys_by_ledger={lid: list(keys)
                             for lid, keys in read_keys.items()},
        write_keys_by_ledger={lid: list(keys)
                              for lid, keys in write_keys.items()},
        lane_sizes=lane_sizes)


def exec_fanout(n_states: int, workers: Optional[int] = None) -> int:
    """Fan-out width for a merged multi-state flush: how many
    independent per-state structural merges are worth running
    concurrently. Pure — a function of the state count and the
    (resolved) worker budget only, so the executor's scheduling
    decision is reproducible and testable without threads. Width 1
    means "stay serial": one state has nothing to overlap, and more
    lanes than workers just queue."""
    if n_states <= 1:
        return 1
    if workers is None:
        from plenum_tpu.runtime.pipeline import resolve_workers
        workers = resolve_workers()
    return max(1, min(int(n_states), int(workers)))
