"""Verification daemon — one process owns the accelerator, every node
offloads ed25519 batch verification to it over a local socket.

Deployment shape for multi-process pools on one host: the TPU is a
process-exclusive device, so co-located node processes cannot each hold
it. The daemon plays the role the CoalescingVerifierHub plays inside a
single process (crypto/batch_verifier.py): requests from all connected
nodes are coalesced within a small window into ONE fused device launch —
the verify kernel is latency-bound, so k separate launches cost ~k× one
fused launch — and results are scattered back per request.

Pipelining: the device call runs on a single worker thread while the
asyncio loop keeps reading frames, so batch k+1 accumulates during batch
k's device round trip (the tunnel RTT is the dominant term on this
hardware).

Wire protocol (both directions): 4-byte little-endian length prefix +
msgpack payload.
  request : [req_id, [[msg, sig, vk], ...]]
  response: [req_id, results_bytes]   (one 0/1 byte per item)

Reference equivalence: the reference verifies inline through libsodium
(plenum/server/client_authn.py:84); this daemon is the tpu-native
replacement for that native-library seam at multi-process scale.
"""
from __future__ import annotations

import asyncio
import logging
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import List, Tuple

import msgpack

from plenum_tpu.observability.tracing import CAT_DEVICE, NullTracer

logger = logging.getLogger(__name__)

LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024
# per-connection response backlog past which the peer is declared stalled
# and dropped: the daemon serves every node on the host, so one wedged
# reader must not buffer the others' memory away
WRITE_HIGH_WATER = 8 * 1024 * 1024


class VerifyDaemon:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "adaptive", window: float = None,
                 bucket: int = None, cpu_floor: int = None):
        """bucket: device launches are chunked to EXACTLY this many items
        (padded by repetition) so XLA compiles ONE batch shape — variable
        shapes would hit a fresh ~100 s compile mid-run. cpu_floor:
        fused batches below this take the OpenSSL path (a near-empty
        device launch costs more than scalar verification). Both only
        apply to device backends; backend="cpu" verifies directly.
        None defaults single-source from Config.VERIFY_DAEMON_* (the
        VERIFIER_BATCH_THRESHOLD precedent); explicit args win."""
        from plenum_tpu.common.config import Config
        from plenum_tpu.crypto.batch_verifier import create_verifier
        self.host = host
        self.port = port
        self._backend_name = backend
        self._verifier = create_verifier(backend)
        self._bucket = Config.VERIFY_DAEMON_BUCKET \
            if bucket is None else bucket
        self._cpu_floor = Config.VERIFY_DAEMON_CPU_FLOOR \
            if cpu_floor is None else cpu_floor
        self._window = Config.VERIFY_DAEMON_WINDOW \
            if window is None else window
        self._queue: asyncio.Queue = asyncio.Queue()
        # worker sizing through the single pipeline knob (PT005: one
        # knob, every consumer). The daemon's FALLBACK is 1, not the
        # node pipeline's cores−1 auto: device launches must serialize
        # anyway, and a busy worker is exactly what lets the NEXT
        # batch coalesce deeper — only an explicit PIPELINE_WORKERS
        # raises it (multi-backend / cpu-path deployments).
        from plenum_tpu.runtime.pipeline import resolve_workers
        self._pool = ThreadPoolExecutor(max_workers=resolve_workers(
            getattr(Config, "PIPELINE_WORKERS", None), fallback=1))
        self._server = None
        self._batcher_task = None
        self._writers = set()
        self.served = 0
        self.launches = 0
        # flight recorder: the daemon runs in its own process, so it
        # gets its own tracer (attach a real one + trace_file to dump
        # Perfetto timelines of coalescing vs device round trips)
        self.tracer = NullTracer("verify-daemon")
        self.trace_file = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._batcher_task = asyncio.get_event_loop().create_task(
            self._batcher())
        logger.info("verify daemon listening on %s:%d", self.host, self.port)

    async def stop(self):
        # cancel the batcher FIRST: left running past shutdown it would
        # keep consuming frames that buffered before the connections die
        # below, answering them all-False through the shut-down pool —
        # and a restarted daemon on the same port never sees them
        if self._batcher_task is not None:
            self._batcher_task.cancel()
            try:
                await self._batcher_task
            except (asyncio.CancelledError, Exception):
                pass
            self._batcher_task = None
        if self._server is not None:
            self._server.close()
            # abort (RST), don't close (FIN-after-flush), live node
            # connections: a graceful close can deliver a final reply
            # ahead of the FIN, so the client keeps dispatching into the
            # dead link instead of re-dialing the restarted daemon.
            # Also required for 3.12's wait_closed(), which waits for
            # EVERY client connection, not just the listener.
            for w in list(self._writers):
                try:
                    w.transport.abort()
                except Exception:
                    pass
            await self._server.wait_closed()
        self._pool.shutdown(wait=False)
        self._dump_trace()

    def _dump_trace(self):
        if self.trace_file is None or not getattr(
                self.tracer, "enabled", False):
            return
        try:
            from plenum_tpu.observability.export import export_chrome_trace
            export_chrome_trace([self.tracer], self.trace_file)
        except Exception:
            logger.warning("trace dump failed", exc_info=True)

    # ------------------------------------------------------------ conns

    def _verify_bucketed(self, items):
        """Fixed-shape device launches: chunk to `bucket` items (pad the
        tail by repetition), dispatch every chunk async FIRST so the
        launches pipeline through the device queue, then collect.

        Multi-chip: the bucket scales by the mesh's device count so one
        fused launch spans every chip (the mesh dispatcher re-buckets
        per device, so the per-device compiled shape is unchanged)."""
        if self._backend_name == "cpu" or self._bucket <= 0 \
                or len(items) < self._cpu_floor:
            return self._verifier.verify_batch(items)
        b = self._bucket
        from plenum_tpu.ops.mesh import get_mesh
        mesh = get_mesh()
        if mesh.should_shard(b * mesh.n_devices):
            # only when the scaled launch actually clears the shard
            # gate — otherwise it would take the passthrough path at a
            # brand-new (uncompiled) shape for zero mesh benefit
            b *= mesh.n_devices
        chunks = [items[i:i + b] for i in range(0, len(items), b)]
        if len(chunks[-1]) < b:
            pad = chunks[-1][0]
            chunks[-1] = chunks[-1] + [pad] * (b - len(chunks[-1]))
        # daemon-seam lane accounting + round trip: real items vs the
        # fixed-bucket grid launched (the tail chunk's repetition
        # padding is this seam's wasted lanes); this method runs on the
        # worker thread start-to-finish, so the wall time here IS the
        # fused dispatch→collect round trip
        from plenum_tpu.observability import telemetry as tmy
        tm_hub = tmy.get_seam_hub()
        first_call = tm_hub.record_launch(
            tmy.SEAM_DAEMON, len(items), b * len(chunks), shape=b)
        t0 = tm_hub.clock()
        pendings = [self._verifier.dispatch(c) for c in chunks]
        out = []
        for p in pendings:
            out.extend(p.collect())
        tm_hub.record_roundtrip(tmy.SEAM_DAEMON,
                                (tm_hub.clock() - t0) * 1e3,
                                first_call=first_call)
        return out[:len(items)]

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        self._writers.add(writer)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = LEN.unpack(hdr)
                if n > MAX_FRAME:
                    logger.warning("oversized frame (%d); closing", n)
                    break
                payload = await reader.readexactly(n)
                try:
                    req_id, items = msgpack.unpackb(payload, raw=False)
                except Exception:
                    # garbage frame: close THIS connection cleanly; an
                    # escaped decode error would kill the reader task
                    # with an unretrieved-exception warning instead
                    logger.warning("undecodable frame; closing",
                                   exc_info=True)
                    break
                await self._queue.put((writer, req_id, items))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    # ---------------------------------------------------------- batching

    async def _batcher(self):
        loop = asyncio.get_event_loop()
        while True:
            first = await self._queue.get()
            batch = [first]
            # event-driven coalescing: sleep exactly until the next frame
            # or the window deadline — a polling loop would burn the one
            # CPU core the node processes need
            with self.tracer.span("coalesce", CAT_DEVICE) as _csp:
                deadline = loop.time() + self._window
                while True:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self._queue.get(), remaining))
                    except asyncio.TimeoutError:
                        break
                _csp.add(requests=len(batch))
            self.tracer.counter("verify_queue_depth", self._queue.qsize())
            all_items: List[Tuple[bytes, bytes, bytes]] = []
            spans = []
            for _, _, items in batch:
                lo = len(all_items)
                try:
                    all_items.extend(
                        (bytes(m), bytes(s), bytes(vk))
                        for m, s, vk in items)
                except Exception:
                    # malformed frame from one client: answer all-False
                    # for ITS span; the batcher must survive (it serves
                    # every node on the host)
                    del all_items[lo:]
                    logger.warning("malformed verify request", exc_info=True)
                spans.append((lo, len(all_items) - lo))
            # dedup byte-identical items across nodes: every node on the
            # host verifies the SAME client requests, so n connected
            # nodes would otherwise cost n× the device work per request
            from plenum_tpu.crypto.batch_verifier import dedup_items
            order, index = dedup_items(all_items)
            # run on the worker thread so the loop keeps reading frames
            # (batch k+1 coalesces during batch k's device round trip)
            t_launch = loop.time()
            logger.debug("batch: %d items (%d unique) from %d requests",
                        len(all_items), len(order), len(batch))
            try:
                # this span IS the device round trip as the loop sees it
                # (the worker thread serializes launches, so a deep span
                # here means the NEXT batch coalesced under it — exactly
                # the pipelining the timeline should show)
                with self.tracer.span("device_verify", CAT_DEVICE,
                                      items=len(all_items),
                                      unique=len(order),
                                      requests=len(batch)):
                    uniq_results = await loop.run_in_executor(
                        self._pool, self._verify_bucketed, order)
                results = [uniq_results[i] for i in index]
            except Exception:  # plenum-lint: disable=PT006 — the daemon
                # serves every node on the host: ANY backend failure
                # must answer all-False and keep the batcher alive
                logger.warning("verify batch failed", exc_info=True)
                results = [False] * len(all_items)
            logger.debug("batch done in %.2fs", loop.time() - t_launch)
            self.served += len(all_items)
            self.launches += 1
            for (writer, req_id, _), (lo, cnt) in zip(batch, spans):
                body = bytes(bytearray(
                    1 if results[lo + i] else 0 for i in range(cnt)))
                frame = msgpack.packb([req_id, body], use_bin_type=True)
                try:
                    if writer.transport.is_closing():
                        continue
                    writer.write(LEN.pack(len(frame)) + frame)
                    # bounded buffering without stalling the batcher on
                    # one slow peer: a connection whose response backlog
                    # passes the high-water mark is aborted (abort, not
                    # close — close would keep the backlog alive trying
                    # to flush it to the stalled reader). Its node fails
                    # in-flight requests to all-False and re-dials — see
                    # RemoteVerifier's failure policy.
                    if writer.transport.get_write_buffer_size() \
                            > WRITE_HIGH_WATER:
                        logger.warning(
                            "dropping stalled verify client "
                            "(write backlog %d bytes)",
                            writer.transport.get_write_buffer_size())
                        self._writers.discard(writer)
                        writer.transport.abort()
                except Exception:
                    pass
            if self.trace_file is not None and self.launches % 25 == 0:
                # periodic (SIGTERM skips stop()), AFTER the replies are
                # written and on a side thread: serializing 64k ring
                # records must neither hold back computed results nor
                # stall the event loop's frame reads — either would
                # distort the very latencies being traced
                await loop.run_in_executor(None, self._dump_trace)


async def run_daemon(host="127.0.0.1", port=0, backend="adaptive",
                     ready_file=None, window: float = None,
                     bucket: int = None, cpu_floor: int = None,
                     trace_file=None):
    daemon = VerifyDaemon(host, port, backend, window=window,
                          bucket=bucket, cpu_floor=cpu_floor)
    if trace_file:
        from plenum_tpu.observability.tracing import Tracer
        from plenum_tpu.ops import mesh as mesh_mod
        daemon.tracer = Tracer("verify-daemon")
        daemon.trace_file = trace_file
        # mesh_dispatch spans + per-device counters from the daemon's
        # device launches land in the same timeline
        mesh_mod.get_mesh().tracer = daemon.tracer
    await daemon.start()
    if ready_file:
        # one-shot startup handshake before any frame is served — not a
        # hot-loop write
        with open(ready_file, "w") as f:  # plenum-lint: disable=PT001
            f.write(str(daemon.port))
    while True:
        await asyncio.sleep(3600)


def main():  # pragma: no cover - exercised via subprocess in bench
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--backend", default="adaptive")
    ap.add_argument("--window", type=float, default=None,
                    help="coalescing window s (default: "
                         "Config.VERIFY_DAEMON_WINDOW)")
    ap.add_argument("--bucket", type=int, default=None,
                    help="device launch bucket (default: "
                         "Config.VERIFY_DAEMON_BUCKET)")
    ap.add_argument("--cpu-floor", type=int, default=None,
                    help="OpenSSL floor (default: "
                         "Config.VERIFY_DAEMON_CPU_FLOOR)")
    ap.add_argument("--ready-file", default=None,
                    help="write the bound port here once listening")
    ap.add_argument("--trace-file", default=None,
                    help="record coalesce/device spans and dump a "
                         "Chrome trace-event JSON here (periodically "
                         "and on clean stop)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    if args.backend != "cpu":
        # persistent XLA compile cache (must go through jax.config — the
        # env var alone is inert here); saves ~100 s per bucket shape on
        # every daemon start after the first
        from plenum_tpu.ops import enable_persistent_compilation_cache
        enable_persistent_compilation_cache()
    asyncio.run(run_daemon(args.host, args.port, args.backend,
                           args.ready_file, args.window, args.bucket,
                           args.cpu_floor, trace_file=args.trace_file))


if __name__ == "__main__":  # pragma: no cover
    main()
