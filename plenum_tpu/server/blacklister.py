"""Peer blacklisting + suspicion reporting.

Reference: plenum/server/blacklister.py (Blacklister/SimpleBlacklister)
+ node.reportSuspiciousNode (node.py:2860). The reference deliberately
does NOT auto-blacklist nodes on suspicions ("TODO: Consider
blacklisting nodes again") because most suspicion codes are not
sender-attributable: under an equivocating primary, honest nodes'
PREPAREs mismatch each other's local PRE-PREPARE (PR_DIGEST_WRONG
against honest senders), and MessageReq re-attributes fetched
PRE-PREPAREs to the primary, letting one byzantine responder frame it.

So: every suspicion is logged and counted per peer; automatic
blacklisting is opt-in (Config.BLACKLIST_ON_SUSPICION) and then applies
only to DUPLICATE_PPR_SENT, the one code whose evidence names its
author. Operators (or future attributable evidence) can always
blacklist explicitly — the traffic filter honors the list either way.
"""
from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from collections import Counter
from typing import Set

from plenum_tpu.consensus.ordering_service import Suspicions

logger = logging.getLogger(__name__)

# codes whose offending evidence provably names its author: two
# conflicting PRE-PREPAREs signed for the same (view, seq), and a
# structurally corrupt flat wire envelope (it arrived whole on that
# peer's authenticated stream — nobody else could have framed it)
AUTO_BLACKLIST_CODES = frozenset({
    Suspicions.DUPLICATE_PPR_SENT,
    Suspicions.WIRE_MALFORMED,
})


class Blacklister(ABC):
    @abstractmethod
    def blacklist(self, name: str) -> None: ...

    @abstractmethod
    def is_blacklisted(self, name: str) -> bool: ...


class SimpleBlacklister(Blacklister):
    def __init__(self, name: str):
        self.name = name
        self.blacklisted: Set[str] = set()
        self.suspicion_counts: Counter = Counter()

    def report_suspicion(self, node: str, code, reason: str,
                         auto_blacklist: bool = False) -> None:
        """reference reportSuspiciousNode: always log + count;
        blacklist only attributable evidence, and only when enabled."""
        self.suspicion_counts[node] += 1
        logger.warning("%s raised suspicion on node %s for %s; code %s",
                       self.name, node, reason, code)
        if auto_blacklist and code in AUTO_BLACKLIST_CODES:
            self.blacklist(node)

    def blacklist(self, name: str) -> None:
        if name not in self.blacklisted:
            logger.warning("%s: blacklisting %s", self.name, name)
        self.blacklisted.add(name)

    def is_blacklisted(self, name: str) -> bool:
        return name in self.blacklisted
