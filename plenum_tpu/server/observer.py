"""Observer framework: validators push committed batches to
non-validating followers that mirror ledgers/state without running
consensus.

Reference: plenum/server/observer/observable.py:11 (Observable — the
node-side policy fanning ObservedData out to registered observers) and
observer_sync_policy_each_batch.py (ObserverSyncPolicyEachBatch — the
observer side: f+1 identical copies of a batch from distinct validators
before applying, strictly in seq-no order).
"""
from __future__ import annotations

import json
import logging
from typing import Callable, Dict, List, Optional, Tuple

from plenum_tpu.common.messages.fields import (
    AnyMapField, LimitedLengthStringField)
from plenum_tpu.common.messages.message_base import MessageBase
from plenum_tpu.common.messages.message_factory import node_message_factory
from plenum_tpu.common.txn_util import get_seq_no, get_type

logger = logging.getLogger(__name__)


class ObservedData(MessageBase):
    """One committed batch as seen by a validator (reference
    plenum/common/messages/node_messages.py ObservedData; policy type
    EACH_BATCH)."""
    typename = "OBSERVED_DATA"
    schema = (
        ("msg_type", LimitedLengthStringField()),
        ("msg", AnyMapField()),
    )


node_message_factory.set_message_class(ObservedData)

BATCH_COMMITTED = "BatchCommitted"


def make_observed_data(ledger_id: int, txns: List[dict]) -> ObservedData:
    return ObservedData(msg_type=BATCH_COMMITTED,
                        msg={"ledgerId": ledger_id, "txns": txns})


class Observable:
    """Validator side: registry of observers + fan-out on commit.
    Policies beyond EACH_BATCH are future work, as in the reference."""

    def __init__(self):
        self._observers: Dict[str, Callable[[ObservedData], None]] = {}

    def add_observer(self, observer_id: str,
                     send_fn: Callable[[ObservedData], None]):
        self._observers[observer_id] = send_fn

    def remove_observer(self, observer_id: str):
        self._observers.pop(observer_id, None)

    @property
    def observer_ids(self) -> List[str]:
        return list(self._observers)

    def batch_committed(self, ledger_id: int, txns: List[dict]):
        if not self._observers or not txns:
            return
        msg = make_observed_data(ledger_id, [dict(t) for t in txns])
        for observer_id, send in list(self._observers.items()):
            try:
                send(msg)
            except Exception:
                logger.warning("observer %s send failed", observer_id,
                               exc_info=True)


class ObserverSyncPolicyEachBatch:
    """Observer side: apply each batch once f+1 distinct validators sent
    an identical copy, strictly in ledger-seq order."""

    def __init__(self, write_manager, database_manager, quorums):
        self._write_manager = write_manager
        self._db = database_manager
        self._quorums = quorums
        # fingerprint -> set of senders, keyed per (ledger, first seq_no)
        self._votes: Dict[Tuple[int, int], Dict[str, set]] = {}
        self._payloads: Dict[str, dict] = {}

    def apply_data(self, msg: ObservedData, sender: str) -> bool:
        """→ True when the batch was applied by this call."""
        if msg.msg_type != BATCH_COMMITTED:
            return False
        data = msg.msg or {}
        txns = data.get("txns") or []
        ledger_id = data.get("ledgerId")
        if not txns or ledger_id is None:
            return False
        first_seq = get_seq_no(txns[0])
        if first_seq is None:
            return False
        ledger = self._db.get_ledger(ledger_id)
        if ledger is None:
            return False
        if first_seq <= ledger.size:
            return False    # already applied
        fp = json.dumps(data, sort_keys=True, default=str)
        key = (int(ledger_id), int(first_seq))
        votes = self._votes.setdefault(key, {})
        votes.setdefault(fp, set()).add(sender)
        self._payloads[fp] = data
        if not self._quorums.observer_data.is_reached(len(votes[fp])):
            return False
        if first_seq != ledger.size + 1:
            return False    # out of order: wait for the gap to fill
        self._apply(int(ledger_id), txns)
        self._forget(key)
        self._try_apply_next(int(ledger_id))
        return True

    def _forget(self, key: Tuple[int, int]):
        """Drop a decided batch's votes AND every variant payload —
        losing fingerprints (forgeries, equivocations) must not
        accumulate for the observer's lifetime."""
        for fp in self._votes.pop(key, {}):
            self._payloads.pop(fp, None)

    def _apply(self, ledger_id: int, txns: List[dict]):
        ledger = self._db.get_ledger(ledger_id)
        state = self._db.get_state(ledger_id)
        for txn in txns:
            ledger.add(dict(txn))
            handler = self._write_manager.request_handlers.get(
                get_type(txn))
            if handler is not None and handler.ledger_id == ledger_id:
                handler.update_state(txn, None, None, is_committed=True)
        if state is not None:
            state.commit()

    def _try_apply_next(self, ledger_id: int):
        """A gap just filled may unblock queued later batches."""
        ledger = self._db.get_ledger(ledger_id)
        while True:
            key = (ledger_id, ledger.size + 1)
            votes = self._votes.get(key)
            if not votes:
                return
            ready_fp = next(
                (fp for fp, senders in votes.items()
                 if self._quorums.observer_data.is_reached(len(senders))),
                None)
            if ready_fp is None:
                return
            data = self._payloads.get(ready_fp)
            if data is None:
                return
            self._apply(ledger_id, data["txns"])
            self._forget(key)


class NodeObserver:
    """A standalone follower: its own storage + handlers, fed
    ObservedData from validators (the reference runs this inside a node
    in observer mode; the aggregate here is independently usable)."""

    def __init__(self, n_validators: int, storage_factory=None,
                 config=None, genesis_txns: Optional[List[dict]] = None):
        from plenum_tpu.consensus.quorums import Quorums
        from plenum_tpu.server.node import NodeBootstrap
        self.db_manager = NodeBootstrap.init_storage(storage_factory,
                                                     config)
        self.write_manager, self.read_manager = \
            NodeBootstrap.init_managers(self.db_manager, config)
        if genesis_txns:
            for txn in genesis_txns:
                handler = self.write_manager.request_handlers.get(
                    get_type(txn))
                if handler is not None:
                    handler.ledger.add(dict(txn))
                    handler.update_state(txn, None, None,
                                         is_committed=True)
                    if handler.state is not None:
                        handler.state.commit()
        self.policy = ObserverSyncPolicyEachBatch(
            self.write_manager, self.db_manager, Quorums(n_validators))

    def apply_data(self, msg: ObservedData, sender: str) -> bool:
        return self.policy.apply_data(msg, sender)
