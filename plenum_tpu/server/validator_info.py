"""Validator status snapshot — the operator's one-stop node dump.

Reference: plenum/server/validator_info_tool.py:54
(ValidatorNodeInfoTool — alias/did, pool counts, ledger sizes + root
hashes, per-replica status, mode, metrics averages, periodic JSON
dump). Same shape here, reading the live Node aggregate.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID, POOL_LEDGER_ID)

_LEDGER_NAMES = {
    POOL_LEDGER_ID: "pool",
    DOMAIN_LEDGER_ID: "domain",
    CONFIG_LEDGER_ID: "config",
    AUDIT_LEDGER_ID: "audit",
}


class ValidatorNodeInfoTool:
    def __init__(self, node, metrics=None, get_time=time.time):
        self._node = node
        self._metrics = metrics
        self._get_time = get_time
        self._started_at = get_time()

    # ------------------------------------------------------------- info

    @property
    def info(self) -> dict:
        node = self._node
        return {
            "alias": node.name,
            "timestamp": int(self._get_time()),
            "uptime_s": int(self._get_time() - self._started_at),
            "Node_info": {
                "Name": node.name,
                "Mode": ("participating" if node.mode_participating
                         else ("syncing" if node.leecher.in_progress
                               else "stalled")),
                "View_no": node.view_no,
                "Last_ordered_3PC": list(node.last_ordered),
                "Master_primary": node.master_primary_name,
                "Count_of_replicas": node.replicas.num_instances,
                "Replicas_status": self._replicas_status(),
                "Committed_ledger_root_hashes": self._ledger_roots(),
                "Committed_state_root_hashes": self._state_roots(),
                "Ledger_sizes": self._ledger_sizes(),
            },
            "Pool_info": self._pool_info(),
            "Software": {"plenum_tpu": _version()},
            "Memory_info": self._memory_info(),
            "Latencies": self._latencies(),
            "Metrics": (self._metrics.summary()
                        if self._metrics is not None
                        and hasattr(self._metrics, "summary") else {}),
        }

    def _memory_info(self) -> dict:
        """Process RSS + GC behavior (reference gc_trackers.py; the
        reference's validator-info memory section reads psutil — here
        it's /proc + the process-wide GcTimeTracker totals)."""
        from plenum_tpu.utils.gc_tracker import (
            GcTimeTracker, process_memory_info)
        out = dict(process_memory_info())
        out["gc"] = GcTimeTracker.instance().snapshot()
        return out

    def _latencies(self) -> dict:
        """Pool- and per-client request latency (reference
        latency_measurements.py:17 — per-client EMAs, high-median
        aggregate)."""
        monitor = getattr(self._node, "monitor", None)
        if monitor is None:
            return {}
        cl = monitor.client_latencies
        return {
            "Avg_latency_s": monitor.avg_latency(),
            "Clients_avg_latency_s": cl.get_avg_latency(),
            "Per_client": cl.per_client(),
        }

    def _replicas_status(self) -> dict:
        out = {}
        for replica in self._node.replicas:
            data = replica.data
            out[str(data.inst_id)] = {
                "Primary": data.primary_name,
                "Watermarks": "{}:{}".format(data.low_watermark,
                                             data.high_watermark),
                "Last_ordered_3PC": list(data.last_ordered_3pc),
            }
        return out

    def _ledger_roots(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out[name] = str(ledger.root_hash)
        return out

    def _state_roots(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            state = self._node.db_manager.get_state(lid)
            if state is not None:
                from plenum_tpu.common.serializers.base58 import b58encode
                out[name] = b58encode(state.committedHeadHash)
        return out

    def _ledger_sizes(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out[name] = ledger.size
        return out

    def _pool_info(self) -> dict:
        node = self._node
        validators = list(node.replica.data.validators)
        quorums = node.replica.data.quorums
        info = {
            "Total_nodes_count": len(validators),
            "f_value": quorums.f,
            "Quorums": repr(quorums),
            "Validators": validators,
        }
        bus = node.network
        connecteds = getattr(bus, "connecteds", None)
        if connecteds is not None:
            reachable = sorted(set(connecteds) | {node.name})
            info["Reachable_nodes"] = reachable
            info["Unreachable_nodes"] = sorted(
                set(validators) - set(reachable))
        return info

    # ------------------------------------------------------------- dump

    def dump_json_file(self, out_dir: str) -> str:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir,
                            "{}_info.json".format(self._node.name.lower()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.info, f, indent=2, default=str)
        os.replace(tmp, path)
        return path


def _version() -> str:
    try:
        from plenum_tpu import __version__
        return __version__
    except ImportError:
        return "dev"
