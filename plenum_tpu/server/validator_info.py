"""Validator status snapshot — the operator's one-stop node dump.

Reference: plenum/server/validator_info_tool.py:54
(ValidatorNodeInfoTool — alias/did, pool counts, ledger sizes + root
hashes, per-replica status, mode, metrics averages, periodic JSON
dump). Same shape here, reading the live Node aggregate.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID, POOL_LEDGER_ID)

_LEDGER_NAMES = {
    POOL_LEDGER_ID: "pool",
    DOMAIN_LEDGER_ID: "domain",
    CONFIG_LEDGER_ID: "config",
    AUDIT_LEDGER_ID: "audit",
}


class ValidatorNodeInfoTool:
    def __init__(self, node, metrics=None, get_time=time.time):
        self._node = node
        self._metrics = metrics
        self._get_time = get_time
        self._started_at = get_time()

    # ------------------------------------------------------------- info

    @property
    def info(self) -> dict:
        node = self._node
        return {
            "alias": node.name,
            "timestamp": int(self._get_time()),
            "uptime_s": int(self._get_time() - self._started_at),
            "Node_info": {
                "Name": node.name,
                "Mode": ("participating" if node.mode_participating
                         else ("syncing" if node.leecher.in_progress
                               else "stalled")),
                "View_no": node.view_no,
                "Last_ordered_3PC": list(node.last_ordered),
                "Master_primary": node.master_primary_name,
                "Count_of_replicas": node.replicas.num_instances,
                "Replicas_status": self._replicas_status(),
                "Committed_ledger_root_hashes": self._ledger_roots(),
                "Committed_state_root_hashes": self._state_roots(),
                "Ledger_sizes": self._ledger_sizes(),
            },
            "Pool_info": self._pool_info(),
            "View_change_info": self._view_change_info(),
            "Catchup_status": self._catchup_status(),
            "Freshness_status": self._freshness_status(),
            "Uncommitted_info": self._uncommitted_info(),
            "Software": {"plenum_tpu": _version(),
                         "python": _python_version(),
                         "jax": _dep_version("jax")},
            "Hardware_info": self._hardware_info(),
            "Config_info": self._config_info(),
            "Memory_info": self._memory_info(),
            "Latencies": self._latencies(),
            "Extractions": self._extractions(),
            "Tracing": self._tracing_info(),
            "Telemetry": self._telemetry_info(),
            "Device_mesh": self._device_mesh_info(),
            "Metrics": (self._metrics.summary()
                        if self._metrics is not None
                        and hasattr(self._metrics, "summary") else {}),
        }

    def _view_change_info(self) -> dict:
        """Reference validator_info_tool View_change_status: whether a
        view change is in flight + the vote state feeding the next."""
        data = self._node.replica.data
        out = {
            "View_No": data.view_no,
            "VC_in_progress": bool(data.waiting_for_new_view),
            "Last_complete_view_no": data.view_no
            if not data.waiting_for_new_view else data.view_no - 1,
        }
        trigger = getattr(self._node.replica, "vc_trigger", None)
        cache = getattr(trigger, "_cache", None)
        if cache is not None and hasattr(cache, "votes_summary"):
            out["IC_queue"] = cache.votes_summary()
        return out

    def _catchup_status(self) -> dict:
        """Per-ledger sync state (reference Catchup_status block)."""
        leecher = getattr(self._node, "leecher", None)
        if leecher is None:
            return {}
        out = {"In_progress": bool(leecher.in_progress),
               "Number_txns_in_catchup": getattr(
                   self._node, "catchup_txns_total", None),
               "Ledger_statuses": {}}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out["Ledger_statuses"][name] = {
                    "size": ledger.size,
                    "root": str(ledger.root_hash)}
        return out

    def _freshness_status(self) -> dict:
        """Last signed-state update per ledger + staleness (reference
        FreshnessChecker view in validator info)."""
        checker = getattr(self._node, "freshness_checker", None)
        if checker is None:
            return {}
        now = self._get_time()
        out = {}
        last = getattr(checker, "_last_updated", {})
        timeout = getattr(checker, "_timeout",
                          getattr(checker, "freshness_timeout", None))
        for lid, ts in last.items():
            name = _LEDGER_NAMES.get(lid, str(lid))
            out[name] = {
                "Last_updated_time": ts,
                "Age_s": round(now - ts, 1),
                "Has_write_consensus": timeout is None
                or (now - ts) <= timeout,
            }
        return out

    def _uncommitted_info(self) -> dict:
        """Staged-but-unordered work: uncommitted txns per ledger and
        ordering queue depths — the numbers that say where a wedged
        pool is stuck."""
        out = {"Uncommitted_txns": {}, "Request_queues": {}}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out["Uncommitted_txns"][name] = len(
                    getattr(ledger, "uncommittedTxns", ()) or ())
        ordering = getattr(self._node.replica, "ordering", None)
        if ordering is not None:
            for lid, queue in getattr(ordering, "requestQueues",
                                      {}).items():
                out["Request_queues"][
                    _LEDGER_NAMES.get(lid, str(lid))] = len(queue)
        reqs = getattr(self._node.propagator, "requests", None)
        if reqs is not None:
            out["In_flight_requests"] = len(reqs)
        return out

    def _tracing_info(self) -> dict:
        """Flight-recorder state (observability/): whether tracing is
        on, ring capacity, records ever written and how many of those
        wrapped out of the buffer — the numbers that say if a dumped
        timeline still covers the window you care about."""
        tracer = getattr(self._node, "tracer", None)
        stats = getattr(tracer, "stats", None)
        return stats() if stats is not None else {}

    def _telemetry_info(self) -> dict:
        """Telemetry-plane snapshot (observability/telemetry.py): the
        node's latency histograms (ordered p50/p99), pool-health gauges
        and recovery counters, plus the process-wide device-seam lane
        accounting (shared across co-resident nodes, like the mesh) —
        the numbers a serving tier is judged on, readable without
        attaching a profiler."""
        hub = getattr(self._node, "telemetry", None)
        if hub is None or not getattr(hub, "enabled", False):
            return {"enabled": False}
        out = hub.snapshot()
        try:
            from plenum_tpu.observability.telemetry import get_seam_hub
            seam = get_seam_hub()
            if getattr(seam, "enabled", False):
                out["device_seams"] = seam.snapshot().get("seams", {})
        except Exception:
            pass
        return out

    def _device_mesh_info(self) -> dict:
        """Device-mesh dispatcher stats (ops/mesh.py): enabled/gate
        knobs, sharded-vs-passthrough dispatch counts, last per-device
        batch. mesh_stats never initializes a backend, so this dump
        stays safe inside an ordering tick (same rule as _dep_version:
        no jax import side effects)."""
        try:
            from plenum_tpu.ops.mesh import mesh_stats
            return mesh_stats()
        except Exception:
            return {}

    def _hardware_info(self) -> dict:
        out = {}
        try:
            st = os.statvfs(".")
            out["HDD_free_Mb"] = st.f_bavail * st.f_frsize // (1 << 20)
        except OSError:
            pass
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable"):
                        out["RAM_available_Mb"] = \
                            int(line.split()[1]) // 1024
                        break
        except OSError:
            pass
        return out

    def _config_info(self) -> dict:
        """The consensus-relevant knobs (reference dumps the whole
        config; the load-bearing subset keeps the file greppable)."""
        cfg = self._node.config
        keys = ("Max3PCBatchSize", "Max3PCBatchWait",
                "Max3PCBatchesInFlight", "CHK_FREQ", "LOG_SIZE",
                "DELTA", "LAMBDA", "OMEGA", "MSG_LEN_LIMIT")
        return {k: getattr(cfg, k, None) for k in keys}

    def _extractions(self) -> dict:
        """Derived rates (reference Extractions block): lifetime write
        throughput from the ordered-txn counter."""
        uptime = max(1e-9, self._get_time() - self._started_at)
        monitor = getattr(self._node, "monitor", None)
        total = getattr(monitor, "total_ordered", 0) if monitor else 0
        return {
            "Total_ordered_requests": total,
            "Avg_write_throughput_rps": round(total / uptime, 2),
            "Master_throughput": (monitor.instance_throughput(0)
                                  if monitor else None),
        }

    def _memory_info(self) -> dict:
        """Process RSS + GC behavior (reference gc_trackers.py; the
        reference's validator-info memory section reads psutil — here
        it's /proc + the process-wide GcTimeTracker totals)."""
        from plenum_tpu.utils.gc_tracker import (
            GcTimeTracker, process_memory_info)
        out = dict(process_memory_info())
        out["gc"] = GcTimeTracker.instance().snapshot()
        return out

    def _latencies(self) -> dict:
        """Pool- and per-client request latency (reference
        latency_measurements.py:17 — per-client EMAs, high-median
        aggregate)."""
        monitor = getattr(self._node, "monitor", None)
        if monitor is None:
            return {}
        cl = monitor.client_latencies
        return {
            "Avg_latency_s": monitor.avg_latency(),
            "Clients_avg_latency_s": cl.get_avg_latency(),
            "Per_client": cl.per_client(),
        }

    def _replicas_status(self) -> dict:
        out = {}
        for replica in self._node.replicas:
            data = replica.data
            out[str(data.inst_id)] = {
                "Primary": data.primary_name,
                "Watermarks": "{}:{}".format(data.low_watermark,
                                             data.high_watermark),
                "Last_ordered_3PC": list(data.last_ordered_3pc),
            }
        return out

    def _ledger_roots(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out[name] = str(ledger.root_hash)
        return out

    def _state_roots(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            state = self._node.db_manager.get_state(lid)
            if state is not None:
                from plenum_tpu.common.serializers.base58 import b58encode
                out[name] = b58encode(state.committedHeadHash)
        return out

    def _ledger_sizes(self) -> dict:
        out = {}
        for lid, name in _LEDGER_NAMES.items():
            ledger = self._node.db_manager.get_ledger(lid)
            if ledger is not None:
                out[name] = ledger.size
        return out

    def _pool_info(self) -> dict:
        node = self._node
        validators = list(node.replica.data.validators)
        quorums = node.replica.data.quorums
        info = {
            "Total_nodes_count": len(validators),
            "f_value": quorums.f,
            "Quorums": repr(quorums),
            "Validators": validators,
        }
        bus = node.network
        connecteds = getattr(bus, "connecteds", None)
        if connecteds is not None:
            reachable = sorted(set(connecteds) | {node.name})
            info["Reachable_nodes"] = reachable
            info["Unreachable_nodes"] = sorted(
                set(validators) - set(reachable))
        return info

    # ------------------------------------------------------------- dump

    def dump_json_file(self, out_dir: str) -> str:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir,
                            "{}_info.json".format(self._node.name.lower()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.info, f, indent=2, default=str)
        os.replace(tmp, path)
        return path


def _version() -> str:
    try:
        from plenum_tpu import __version__
        return __version__
    except ImportError:
        return "dev"


def _python_version() -> str:
    import sys
    return sys.version.split()[0]


def _dep_version(name: str):
    """Installed version WITHOUT importing the package — importing
    jax inside the periodic info dump would stall an ordering tick
    (and can initialize a device runtime as a side effect)."""
    try:
        from importlib.metadata import version
        return version(name)
    except Exception:
        return None
