"""ThreePCOutbox — per-node coalescing of broadcast 3PC votes.

One node broadcasts a PRE-PREPARE (primary), a PREPARE and a COMMIT per
in-flight batch PER PROTOCOL INSTANCE (f+1 RBFT instances); before this
every vote was its own ExternalBus send — its own transport delivery and
its own receive-side handler dispatch on every peer. The outbox collects
every instance's broadcast votes during a prod tick and flushes them as
ONE `ThreePCBatch` wire message (one msgpack pack on the socket path,
one SimNetwork delivery per peer in tests), which the receiving node
routes into the columnar `process_*_batch` intake.

Correctness notes:

* FIFO send order is preserved inside the envelope — a sender enqueues
  PRE-PREPARE before its own PREPARE before its own COMMIT, so per-
  sender causality on the wire is identical to the per-message path.
* Only BROADCAST sends coalesce (3PC votes are always broadcast);
  directed messages (OldViewPrePrepareReply, MessageRep, ...) never
  enter the outbox.
* While a fault-injection tap is installed on the bus
  (testing/adversary), flush degrades to per-message sends: the
  adversary behaviors match and rewrite individual Prepare/Commit/
  PrePrepare messages, and hiding them inside an envelope would blind
  the fault injector — per-message wire granularity IS the seam there.
* Batches are chunked under a serialized-size budget so a full tick of
  votes can never build a frame the transport would drop wholesale
  (same rule as Propagator.BATCH_SIZE_BUDGET).
"""
from __future__ import annotations

import logging
from typing import List

from plenum_tpu.common.messages.node_messages import (
    Commit, PrePrepare, ThreePCBatch)
from plenum_tpu.observability.tracing import CAT_3PC, NullTracer

logger = logging.getLogger(__name__)

# conservative serialized-size estimates per vote type (bytes): roots +
# digests dominate a PREPARE; a PRE-PREPARE adds ~72 wire bytes per
# request digest (see OrderingService's frame clamp, which bounds the
# reqIdr contribution a single PP can carry)
_PREPARE_EST = 640
_COMMIT_EST = 384
_PP_BASE_EST = 1024
_PP_PER_DIGEST_EST = 72


def _estimate(msg) -> int:
    if isinstance(msg, PrePrepare):
        return _PP_BASE_EST + _PP_PER_DIGEST_EST * len(msg.reqIdr)
    if isinstance(msg, Commit):
        return _COMMIT_EST
    return _PREPARE_EST


class ThreePCOutbox:
    # entry-count cap per envelope; the size budget is the real guard
    BATCH_LIMIT = 300

    def __init__(self, network, msg_len_limit: int = 128 * 1024):
        self._network = network
        # generous envelope/AEAD headroom, like the propagator's budget
        self._size_budget = msg_len_limit - 8 * 1024
        self._out: List = []
        self.tracer = NullTracer()   # node injects the real one
        self.flushed_batches = 0
        self.flushed_msgs = 0

    def queue(self, msg) -> None:
        """Collect one broadcast 3PC vote for the next flush."""
        self._out.append(msg)

    def __len__(self) -> int:
        return len(self._out)

    def flush(self) -> int:
        """Ship everything queued since the last flush. → votes sent."""
        if not self._out:
            return 0
        out, self._out = self._out, []
        with self.tracer.span("three_pc_flush", CAT_3PC, n=len(out)):
            self._flush(out)
        self.flushed_msgs += len(out)
        return len(out)

    def _flush(self, out: List) -> None:
        send = self._network.send
        if getattr(self._network, "has_tap", False):
            # fault injection installed: keep per-message granularity
            for m in out:
                send(m)
            return
        if len(out) == 1:
            send(out[0])
            return
        chunk, chunk_size = [], 0
        for m in out:
            size = _estimate(m)
            if chunk and (len(chunk) >= self.BATCH_LIMIT
                          or chunk_size + size > self._size_budget):
                send(ThreePCBatch(messages=chunk))
                self.flushed_batches += 1
                chunk, chunk_size = [], 0
            chunk.append(m)
            chunk_size += size
        if chunk:
            if len(chunk) == 1:
                send(chunk[0])
            else:
                send(ThreePCBatch(messages=chunk))
                self.flushed_batches += 1
