"""ThreePCOutbox — per-node coalescing of broadcast 3PC votes.

One node broadcasts a PRE-PREPARE (primary), a PREPARE and a COMMIT per
in-flight batch PER PROTOCOL INSTANCE (f+1 RBFT instances); before this
every vote was its own ExternalBus send — its own transport delivery and
its own receive-side handler dispatch on every peer. The outbox collects
every instance's broadcast votes during a prod tick and flushes them as
ONE wire message per peer: a flat zero-copy ``FlatBatch`` envelope
(common/serializers/flat_wire.py — PREPARE/COMMIT votes as contiguous
typed columns, PRE-PREPAREs as a length-prefixed section; one pack for
the whole tick) when ``Config.FLAT_WIRE`` is on, else the typed
``ThreePCBatch`` envelope (one msgpack pack on the socket path). The
receiving node routes flat envelopes into the columnar
``process_*_columns`` intake with zero intermediate message objects,
typed envelopes into ``process_*_batch``.

Correctness notes:

* FIFO send order is preserved inside the envelope — a sender enqueues
  PRE-PREPARE before its own PREPARE before its own COMMIT, so per-
  sender causality on the wire is identical to the per-message path
  (the receiver processes each envelope phase-major per instance, and
  no sender emits a vote before its own earlier-phase vote for the
  same key).
* Only BROADCAST sends coalesce (3PC votes are always broadcast);
  directed messages (OldViewPrePrepareReply, MessageRep, ...) never
  enter the outbox.
* While a fault-injection tap is installed on the bus
  (testing/adversary), flush degrades to per-message sends: the
  adversary behaviors match and rewrite individual Prepare/Commit/
  PrePrepare messages, and hiding them inside an envelope would blind
  the fault injector — per-message wire granularity IS the seam there.
* Batches are chunked under a serialized-size budget so a full tick of
  votes can never build a frame the transport would drop wholesale
  (same rule as Propagator.BATCH_SIZE_BUDGET). The per-vote byte
  estimates are MEASURED: an EWMA per vote type updated from the
  actual packed section sizes of every flat flush (seeded from the
  legacy hand-tuned constants), with a hard post-encode split when an
  estimate lags — the chunking budget tracks whatever the wire layout
  actually costs, it is never hand-tuned again. The measured sizes
  also land in the process seam hub as per-vote-type histograms
  (TM.WIRE_VOTE_BYTES_*) next to the wire byte counters.
"""
from __future__ import annotations

import logging
from typing import List

from plenum_tpu.common.messages.node_messages import (
    Commit, FlatBatch, PrePrepare, ThreePCBatch)
from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.observability.tracing import CAT_3PC, NullTracer
from plenum_tpu.observability.telemetry import TM, get_seam_hub

logger = logging.getLogger(__name__)

# seed estimates per vote type (bytes) — the starting point of the
# rolling measured model below, NOT the operating values: after the
# first flat flush every estimate is an EWMA of actual packed bytes
_PREPARE_SEED = 640
_COMMIT_SEED = 384
_PP_BASE_SEED = 1024
# wire bytes one request digest adds to a PRE-PREPARE (the reqIdr
# entry); kept constant — it is bounded by digest length + framing
_PP_PER_DIGEST_EST = 72


class EnvelopeSizeModel:
    """Rolling measured per-vote packed sizes. ``estimate`` drives the
    chunking budget; ``note_*`` feed it the actual section payload
    sizes each flat flush produces (EWMA, alpha=0.25) and record the
    per-vote byte histograms into the process seam hub."""

    ALPHA = 0.25

    def __init__(self):
        self.prepare = float(_PREPARE_SEED)
        self.commit = float(_COMMIT_SEED)
        self.pp_base = float(_PP_BASE_SEED)

    def _ewma(self, cur: float, measured: float) -> float:
        return cur + self.ALPHA * (measured - cur)

    def note_prepares(self, payload_len: int, count: int) -> None:
        per = payload_len / count
        self.prepare = self._ewma(self.prepare, per)
        get_seam_hub().observe(TM.WIRE_VOTE_BYTES_PREPARE, per)

    def note_commits(self, payload_len: int, count: int) -> None:
        per = payload_len / count
        self.commit = self._ewma(self.commit, per)
        get_seam_hub().observe(TM.WIRE_VOTE_BYTES_COMMIT, per)

    def note_preprepares(self, payload_len: int, count: int,
                         digests: int) -> None:
        per = payload_len / count
        base = max(64.0, per - _PP_PER_DIGEST_EST * (digests / count))
        self.pp_base = self._ewma(self.pp_base, base)
        get_seam_hub().observe(TM.WIRE_VOTE_BYTES_PREPREPARE, per)

    def estimate(self, msg) -> int:
        if isinstance(msg, PrePrepare):
            return int(self.pp_base
                       + _PP_PER_DIGEST_EST * len(msg.reqIdr))
        if isinstance(msg, Commit):
            return int(self.commit)
        return int(self.prepare)


class ThreePCOutbox:
    # entry-count cap per envelope; the size budget is the real guard
    BATCH_LIMIT = 300

    def __init__(self, network, msg_len_limit: int = 128 * 1024,
                 flat_wire_enabled: bool = True):
        self._network = network
        # generous envelope/AEAD headroom, like the propagator's budget
        self._size_budget = msg_len_limit - 8 * 1024
        self._out: List = []
        self._flat = flat_wire_enabled
        self.size_model = EnvelopeSizeModel()
        self.tracer = NullTracer()   # node injects the real one
        # journey plane: node sets origin + trace_context from config;
        # stamps flow only when the node's tracer is live, so the
        # default NullTracer keeps this seam free
        self.origin = ""
        self.trace_context = False
        self._flush_seq = 0
        self.flushed_batches = 0
        self.flushed_msgs = 0

    def _next_stamp(self):
        """Advisory causal stamp for ONE outgoing envelope, or None
        when trace context is off. The clock pair is sampled HERE, at
        the flush seam (called only from the node's service loop, never
        from consensus logic) — flat_wire's encode half is a PT012
        consensus root and only ever sees the timestamps as plain
        arguments."""
        if not (self.trace_context and self.tracer.enabled):
            return None
        self._flush_seq += 1
        perf, wall = self.tracer.clock_pair()
        return flat_wire.TraceStamp(self.origin, self._flush_seq,
                                    perf, wall)

    def _note_send(self, stamp, n: int, nbytes: int) -> None:
        """Send-side anchor for the journey joiner / Perfetto flow
        arrows: one instant per stamped envelope, keyed by flush seq."""
        if stamp is not None:
            self.tracer.instant("wire_send", CAT_3PC,
                                key=str(stamp.seq), seq=stamp.seq,
                                n=n, nbytes=nbytes)

    def queue(self, msg) -> None:
        """Collect one broadcast 3PC vote for the next flush."""
        self._out.append(msg)

    def __len__(self) -> int:
        return len(self._out)

    def flush(self) -> int:
        """Ship everything queued since the last flush. → votes sent."""
        if not self._out:
            return 0
        out, self._out = self._out, []
        with self.tracer.span("three_pc_flush", CAT_3PC, n=len(out)):
            self._flush(out)
        self.flushed_msgs += len(out)
        return len(out)

    def _flush(self, out: List) -> None:
        send = self._network.send
        if getattr(self._network, "has_tap", False):
            # fault injection installed: keep per-message granularity
            for m in out:
                send(m)
            return
        if self._flat:
            self._flush_flat(out, send)
            return
        self._flush_typed(out, send)

    # ------------------------------------------------------- flat wire

    def _flush_flat(self, out: List, send) -> None:
        for chunk in self._chunks(out):
            try:
                self._send_flat_chunk(chunk, send)
            except flat_wire.FlatWireUnencodable as e:
                # a field value the flat layout cannot carry: THIS
                # chunk rides the validated typed fallback (already-
                # sent chunks stay sent — chunking is FIFO-safe)
                logger.debug("3PC outbox: flat encode fell back (%s)", e)
                self._flush_typed(chunk, send)

    def _chunks(self, out: List):
        estimate = self.size_model.estimate
        chunk, chunk_size = [], 0
        for m in out:
            size = estimate(m)
            if chunk and (len(chunk) >= self.BATCH_LIMIT
                          or chunk_size + size > self._size_budget):
                yield chunk
                chunk, chunk_size = [], 0
            chunk.append(m)
            chunk_size += size
        if chunk:
            yield chunk

    def _send_flat_chunk(self, chunk: List, send) -> None:
        stamp = self._next_stamp()
        with self.tracer.span("wire_pack", CAT_3PC, n=len(chunk)):
            payload, sections = self._encode_chunk(chunk, stamp)
        if len(payload) > self._size_budget and len(chunk) > 1:
            # an estimate lagged the measured sizes: split and re-pack
            # rather than building a frame the transport drops. The
            # oversize attempt's sizes are NOT noted — only envelopes
            # that actually ship feed the model/histograms, or every
            # re-split would count the same votes twice
            half = len(chunk) // 2
            self._send_flat_chunk(chunk[:half], send)
            self._send_flat_chunk(chunk[half:], send)
            return
        self._note_sections(sections)
        hub = get_seam_hub()
        hub.count(TM.WIRE_BYTES_SENT, len(payload))
        hub.observe(TM.WIRE_ENV_BYTES_3PC, len(payload))
        self._note_send(stamp, len(chunk), len(payload))
        send(FlatBatch(payload=payload))
        self.flushed_batches += 1

    def _encode_chunk(self, chunk: List, stamp=None):
        """→ (envelope bytes, [(kind, count, payload_len, digests)])
        — measurement is deferred to _note_sections so only SENT
        envelopes feed the size model."""
        pps = [m for m in chunk if isinstance(m, PrePrepare)]
        commits = [m for m in chunk if isinstance(m, Commit)]
        prepares = [m for m in chunk
                    if not isinstance(m, (PrePrepare, Commit))]
        sections = []
        if pps:
            payload = flat_wire.encode_preprepares(pps)
            sections.append((flat_wire.KIND_PREPREPARE, len(pps),
                             payload, sum(len(p.reqIdr) for p in pps)))
        if prepares:
            sections.append((flat_wire.KIND_PREPARE, len(prepares),
                             flat_wire.encode_prepares(prepares), 0))
        if commits:
            sections.append((flat_wire.KIND_COMMIT, len(commits),
                             flat_wire.encode_commits(commits), 0))
        trace = None
        if stamp is not None:
            trace = flat_wire.encode_trace_stamp(
                stamp.origin, stamp.seq, stamp.perf_ts, stamp.wall_ts)
        return flat_wire.build_envelope(
            [(kind, count, payload)
             for kind, count, payload, _ in sections],
            trace=trace), sections

    def _note_sections(self, sections) -> None:
        model = self.size_model
        for kind, count, payload, digests in sections:
            if kind == flat_wire.KIND_PREPARE:
                model.note_prepares(len(payload), count)
            elif kind == flat_wire.KIND_COMMIT:
                model.note_commits(len(payload), count)
            elif kind == flat_wire.KIND_PREPREPARE:
                model.note_preprepares(len(payload), count, digests)

    # --------------------------------------------- typed-object fallback

    def _flush_typed(self, out: List, send) -> None:
        for chunk in self._chunks(out):
            if len(chunk) == 1:
                # bare single-vote sends carry no stamp — the context
                # is advisory and the envelope kinds are its carriers
                send(chunk[0])
            else:
                stamp = self._next_stamp()
                send(ThreePCBatch(
                    messages=chunk,
                    traceCtx=stamp.as_list() if stamp else None))
                self._note_send(stamp, len(chunk), 0)
                self.flushed_batches += 1
