"""Catchup — ledger synchronization (leecher + seeder).

Reference: plenum/server/catchup/ — SeederService (seeder_service.py:14,
answers LedgerStatus/CatchupReq with txns + consistency proofs),
ConsProofService (cons_proof_service.py:24, agrees on a target size+root
from peer evidence), CatchupRepService (catchup_rep_service.py:18,
fetches txn ranges split across peers and verifies them against the
agreed root), NodeLeecherService (node_leecher_service.py:21, the state
machine ordering ledgers: audit → pool → config → domain,
docs/source/catchup.md:14).

Verification model: the target (size, root) is fixed by a quorum of
ConsistencyProofs; fetched txns are replayed into a shadow merkle tree
and accepted only if the resulting root matches the agreed target root —
the root binds every byte, so a lying seeder can delay but never corrupt
(a failed range is re-requested from other peers).
"""
from __future__ import annotations

import logging
import zlib
from collections import defaultdict
from enum import Enum, auto
from typing import Callable, Dict, List, Optional, Set, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID, POOL_LEDGER_ID)
from plenum_tpu.common.messages.internal_messages import CatchupFinished
from plenum_tpu.common.messages.node_messages import (
    CatchupRep, CatchupReq, ConsistencyProof, LedgerStatus)
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.ledger.merkle_verifier import MerkleVerifier
from plenum_tpu.ledger.tree_hasher import TreeHasher
from plenum_tpu.observability.tracing import CAT_RECOVERY, NullTracer
from plenum_tpu.runtime.timer import TimerService

logger = logging.getLogger(__name__)

CATCHUP_LEDGER_ORDER = [AUDIT_LEDGER_ID, POOL_LEDGER_ID, CONFIG_LEDGER_ID,
                        DOMAIN_LEDGER_ID]


class SeederService:
    """Answers peers' catchup questions from our committed ledgers."""

    def __init__(self, db_manager, network, name: str = "?",
                 view_source: Callable[[], Tuple[int, int]] = None,
                 config: Optional[Config] = None):
        """view_source() → (view_no, last_ordered_pp_seq_no): stamped on
        responses so a rejoining node can adopt the POOL's current view —
        the audit ledger alone records only original (pre-view-change)
        views (reference: LedgerStatus carries viewNo/ppSeqNo)."""
        self._db = db_manager
        self._network = network
        self.name = name
        self._view_source = view_source or (lambda: (0, 0))
        self._config = config or Config()
        network.subscribe(LedgerStatus, self.process_ledger_status)
        network.subscribe(CatchupReq, self.process_catchup_req)

    def _own_status(self, lid: int) -> LedgerStatus:
        # a non-None viewNo marks this as a RESPONSE: seeders only answer
        # solicitations (viewNo None), so two up-to-date peers can never
        # ping-pong statuses at each other forever
        ledger = self._db.get_ledger(lid)
        view_no, pp_seq_no = self._view_source()
        return LedgerStatus(ledgerId=lid, txnSeqNo=ledger.size,
                            viewNo=view_no, ppSeqNo=pp_seq_no,
                            merkleRoot=ledger.root_hash,
                            protocolVersion=2)

    def process_ledger_status(self, status: LedgerStatus, frm: str):
        if status.viewNo is not None:
            return  # a response to someone's solicitation, not for us
        ledger = self._db.get_ledger(status.ledgerId)
        if ledger is None:
            return
        if status.txnSeqNo < ledger.size:
            # requester is behind: prove our extension over their prefix
            proof = self._build_consistency_proof(
                status.ledgerId, status.txnSeqNo, ledger.size)
            if proof is not None:
                self._network.send(proof, [frm])
        else:
            # same or ahead: echo our status so they can count the quorum
            self._network.send(self._own_status(status.ledgerId), [frm])

    def _build_consistency_proof(self, lid: int, start: int, end: int
                                 ) -> Optional[ConsistencyProof]:
        ledger = self._db.get_ledger(lid)
        try:
            if start == 0:
                # a proof from the empty prefix is trivially empty
                # (RFC 6962: PROOF(0, D[n]) = {}); the new root alone
                # carries the commitment
                hashes = []
                old_root = Ledger.hashToStr(ledger.hasher.hash_empty())
            else:
                hashes = [Ledger.hashToStr(h) for h in
                          ledger.tree.consistency_proof(start, end)]
                old_root = Ledger.hashToStr(
                    ledger.tree.merkle_tree_hash(0, start))
        except Exception:
            logger.warning("%s cannot build consistency proof %s..%s",
                           self.name, start, end)
            return None
        view_no, pp_seq_no = self._view_source()
        return ConsistencyProof(
            ledgerId=lid, seqNoStart=start, seqNoEnd=end,
            viewNo=view_no, ppSeqNo=pp_seq_no,
            oldMerkleRoot=old_root, newMerkleRoot=ledger.root_hash,
            hashes=hashes)

    def _catchup_audit_paths(self, ledger: Ledger, start: int, end: int,
                             till: int) -> Optional[Dict[str, List[str]]]:
        """Per-txn inclusion proofs for the served range against the
        size-`till` prefix tree the leecher agreed on. ONE batched pass:
        the proofs share a subtree memo on the host path and ride the
        pipelined device engine above the routing threshold (the
        catchup rep server is a production proof-batch consumer). A
        digest→b58 memo collapses the heavily shared upper siblings."""
        if not (start <= end <= till <= ledger.size and till > 0):
            return None  # we cannot prove against a tree we don't have
        try:
            paths = ledger.tree.inclusion_proofs_batch(
                list(range(start - 1, end)), till)
        except Exception:
            logger.warning("%s cannot build catchup audit paths "
                           "%s..%s@%s", self.name, start, end, till,
                           exc_info=True)
            return None
        to_str = Ledger.hashToStr
        memo: Dict[bytes, str] = {}

        def enc(h):
            s = memo.get(h)
            if s is None:
                s = memo[h] = to_str(h)
            return s

        return {str(seq): [enc(h) for h in path]
                for seq, path in zip(range(start, end + 1), paths)}

    def process_catchup_req(self, req: CatchupReq, frm: str):
        ledger = self._db.get_ledger(req.ledgerId)
        if ledger is None:
            return
        end = min(req.seqNoEnd, ledger.size)
        if end < req.seqNoStart:
            return
        start = req.seqNoStart
        till = req.catchupTill or end
        # chunked reps: a large range leaves as several bounded
        # messages, each independently verifiable from its audit paths.
        # Proofs are materialized per GROUP (a few chunks — large
        # enough to engage the device routing, small enough to bound
        # memory to the group, not the whole requested range).
        conf = self._config
        chunk = max(1, getattr(conf, "CATCHUP_REP_CHUNK",
                               Config.CATCHUP_REP_CHUNK))
        group = max(chunk, getattr(conf, "MERKLE_DEVICE_PROOF_MIN",
                                   Config.MERKLE_DEVICE_PROOF_MIN))
        want_paths = getattr(conf, "CATCHUP_REP_AUDIT_PATHS",
                             Config.CATCHUP_REP_AUDIT_PATHS)
        for glo in range(start, end + 1, group):
            ghi = min(glo + group - 1, end)
            proofs = self._catchup_audit_paths(ledger, glo, ghi, till) \
                if want_paths else None
            for lo in range(glo, ghi + 1, chunk):
                hi = min(lo + chunk - 1, ghi)
                txns = {}
                for seq in range(lo, hi + 1):
                    txn = ledger.getBySeqNo(seq)
                    if txn is None:
                        return
                    txns[str(seq)] = txn
                audit = {k: proofs[k] for k in txns} if proofs else None
                self._network.send(
                    CatchupRep(ledgerId=req.ledgerId, txns=txns,
                               consProof=[], auditPaths=audit), [frm])


class LeecherState(Enum):
    IDLE = auto()
    SYNCING = auto()
    DONE = auto()


class LedgerLeecher:
    """Catchup driver for ONE ledger: cons-proof phase then rep phase."""

    def __init__(self, lid: int, db_manager, network, timer: TimerService,
                 quorums_source: Callable[[], Quorums],
                 on_txn: Callable[[int, dict], None],
                 on_done: Callable[[int], None],
                 config: Optional[Config] = None,
                 view_tracker: Optional[Dict[str, int]] = None,
                 bad_peers: Optional[Set[str]] = None,
                 record: Callable[..., None] = None,
                 name: str = "?"):
        # peer → highest view_no that peer has reported (shared across
        # ledgers by NodeLeecherService; feeds pool_view_estimate)
        self._view_tracker = view_tracker if view_tracker is not None \
            else {}
        # peers whose reps failed audit-path verification (shared across
        # ledgers: a seeder lying about one ledger is not trusted with
        # the others either); chunk assignment skips them
        self._bad_peers = bad_peers if bad_peers is not None else set()
        # recovery-trace hook: record(event_name, **args) → flight
        # recorder instant (NodeLeecherService wires the node tracer)
        self._record = record or (lambda event, **args: None)
        # per-NODE jitter salt: without it every node computes the
        # identical jittered delay for the same (lid, retry) and the
        # pool re-requests in lockstep anyway — crc32, not hash(),
        # because str hashing is randomized per process and would break
        # seeded-sim replay
        self._jitter_salt = zlib.crc32(name.encode())
        self.lid = lid
        self._db = db_manager
        self._network = network
        self._timer = timer
        self._quorums = quorums_source
        self._on_txn = on_txn
        self._on_done = on_done
        self._config = config or Config()
        self.state = LeecherState.IDLE
        self._statuses_same: Set[str] = set()
        self._cons_proofs: Dict[Tuple, Set[str]] = defaultdict(set)
        self.target_size: Optional[int] = None
        self.target_root: Optional[str] = None
        self._buffer: Dict[int, dict] = {}
        # retry machinery: one-shot self-rescheduling with capped
        # exponential backoff (NOT a fixed-period RepeatingTimer — see
        # plenum-lint PT007); the generation guard makes stale scheduled
        # callbacks no-ops after stop()/restart, and the kept closure
        # reference lets stop() actually cancel the heap entry (a
        # backoff-max delay would otherwise sit there ~75s post-catchup)
        self.retry_count = 0
        self._retry_gen = 0
        self._retry_cb = None
        self.next_retry_delay: Optional[float] = None

    @property
    def ledger(self) -> Ledger:
        return self._db.get_ledger(self.lid)

    # ------------------------------------------------------------- start

    def start(self):
        self.state = LeecherState.SYNCING
        self._statuses_same = set()
        self._cons_proofs.clear()
        self._buffer.clear()
        self.target_size = None
        self.target_root = None
        self.retry_count = 0
        self._broadcast_status()
        self._schedule_retry()

    def _broadcast_status(self):
        ledger = self.ledger
        self._network.send(LedgerStatus(
            ledgerId=self.lid, txnSeqNo=ledger.size, viewNo=None,
            ppSeqNo=None, merkleRoot=ledger.root_hash, protocolVersion=2))

    def stop(self):
        self._retry_gen += 1  # belt: any uncancelled retry is a no-op
        if self._retry_cb is not None:
            self._timer.cancel(self._retry_cb)
            self._retry_cb = None
        self.state = LeecherState.DONE

    def _finish(self):
        self.stop()
        self._on_done(self.lid)

    # ------------------------------------------------- retry + backoff

    def _retry_delay(self) -> float:
        """Capped exponential backoff with deterministic jitter:
        retry i waits min(base * 2^i, cap) plus up to JITTER_FRAC of
        that. Jitter derives from (node-name salt, lid, retry) — int
        tuples hash stably in CPython, and the crc32 salt makes it
        differ ACROSS nodes — so the whole fault pattern replays
        bit-identically under a seeded sim while the pool's re-request
        bursts desynchronize (N laggards starting catchup together must
        not hammer the seeders in lockstep). Progress resets
        retry_count (and with it the delay) to the base."""
        conf = self._config
        base = float(conf.CATCHUP_TXN_TIMEOUT)
        cap = float(getattr(conf, "CATCHUP_RETRY_BACKOFF_MAX",
                            Config.CATCHUP_RETRY_BACKOFF_MAX))
        frac = float(getattr(conf, "CATCHUP_RETRY_JITTER_FRAC",
                             Config.CATCHUP_RETRY_JITTER_FRAC))
        delay = min(cap, base * (2 ** min(self.retry_count, 16)))
        unit = (hash((self._jitter_salt, self.lid,
                      self.retry_count)) & 0xFFFF) / 65536.0
        return delay * (1.0 + frac * unit)

    def _schedule_retry(self):
        self._retry_gen += 1
        gen = self._retry_gen
        if self._retry_cb is not None:
            self._timer.cancel(self._retry_cb)
        delay = self._retry_delay()
        self.next_retry_delay = delay

        def fire():
            if gen != self._retry_gen \
                    or self.state != LeecherState.SYNCING:
                return
            self._retry()

        self._retry_cb = fire
        self._timer.schedule(delay, fire)

    def _retry(self):
        if self.state != LeecherState.SYNCING:
            return
        # count BEFORE re-requesting so the very first retry already
        # rotates the chunk assignment off whichever peer just starved
        # it (and the next wait doubles)
        self.retry_count += 1
        self._record("catchup_retry", lid=self.lid,
                     retry=self.retry_count,
                     delay=round(self.next_retry_delay or 0.0, 3),
                     bad_peers=len(self._bad_peers))
        if self.target_size is None:
            self._broadcast_status()
        else:
            self._request_missing()
        self._schedule_retry()

    def _note_progress(self):
        """A peer answered usefully (target adopted / txns buffered):
        the backoff restarts from the base period. The pending retry is
        re-armed too — resetting only the counter would leave an
        escalated (up-to-cap) delay already sitting in the timer heap,
        so a chunk still missing (stalling seeder) would wait out the
        stale long window even though the pool just proved responsive."""
        if self.retry_count:
            self.retry_count = 0
            self._schedule_retry()

    # ----------------------------------------------------- status phase

    def process_ledger_status(self, status: LedgerStatus, frm: str):
        if self.state != LeecherState.SYNCING or status.ledgerId != self.lid:
            return
        if status.viewNo is not None:
            self._view_tracker[frm] = max(
                self._view_tracker.get(frm, 0), status.viewNo)
        ledger = self.ledger
        # "same" means same size AND same root — an equal-size peer with a
        # different root is divergence, not agreement
        if status.txnSeqNo == ledger.size \
                and status.merkleRoot == ledger.root_hash:
            self._statuses_same.add(frm)
            if self._quorums().ledger_status.is_reached(
                    len(self._statuses_same)) and self.target_size is None:
                self._finish()

    def process_consistency_proof(self, proof: ConsistencyProof, frm: str):
        if self.state != LeecherState.SYNCING or proof.ledgerId != self.lid:
            return
        if proof.viewNo is not None:
            self._view_tracker[frm] = max(
                self._view_tracker.get(frm, 0), proof.viewNo)
        if proof.seqNoStart != self.ledger.size:
            return
        key = (proof.seqNoStart, proof.seqNoEnd, proof.newMerkleRoot)
        self._cons_proofs[key].add(frm)
        quorum = self._quorums().consistency_proof
        agreed = [k for k, votes in self._cons_proofs.items()
                  if quorum.is_reached(len(votes))]
        if not agreed:
            return
        # go for the largest agreed extension
        start, end, root = max(agreed, key=lambda k: k[1])
        if self.target_size is None or end > self.target_size:
            self.target_size = end
            self.target_root = root
            self._note_progress()
            self._request_missing()

    # -------------------------------------------------------- rep phase

    def _request_missing(self):
        if self.target_size is None:
            return
        start = self.ledger.size + 1
        missing = [s for s in range(start, self.target_size + 1)
                   if s not in self._buffer]
        if not missing:
            self._try_apply()
            return
        connecteds = sorted(self._network.connecteds)
        # skip peers whose reps failed proof verification; if that
        # leaves nobody, fall back to everyone (a wrongly-blamed pool
        # beats a stalled catchup — the root check still protects us)
        peers = [p for p in connecteds if p not in self._bad_peers] \
            or connecteds or [None]
        # rotate assignment by retry round: a dead or silently lying
        # peer must not keep receiving the same chunk forever (the
        # pre-rotation deterministic split starved exactly like that)
        rot = self.retry_count % len(peers)
        peers = peers[rot:] + peers[:rot]
        # split contiguous chunks across peers
        chunk = max(1, (len(missing) + len(peers) - 1) // len(peers))
        for i, peer in enumerate(peers):
            lo = i * chunk
            if lo >= len(missing):
                break
            hi = min(lo + chunk, len(missing)) - 1
            req = CatchupReq(ledgerId=self.lid,
                             seqNoStart=missing[lo],
                             seqNoEnd=missing[hi],
                             catchupTill=self.target_size)
            self._network.send(req, [peer] if peer else None)

    def _verify_rep_proofs(self, rep: CatchupRep, frm: str) -> bool:
        """Per-rep fast rejection: when the seeder attached audit paths,
        verify every txn's inclusion against the quorum-agreed
        (target_size, target_root) BEFORE buffering — a lying chunk is
        dropped (and re-requested elsewhere) at rep time instead of
        poisoning the buffer until the whole-range root replay. Leaf
        hashing batches through the TreeHasher TPU seam. Legacy reps
        without paths still ride the final root check."""
        paths = getattr(rep, "auditPaths", None)
        if not paths or self.target_root is None:
            return True
        ledger = self.ledger
        try:
            items = []
            for seq_str, txn in rep.txns.items():
                seq = int(seq_str)
                if not ledger.size < seq <= self.target_size:
                    continue
                path_strs = paths.get(seq_str)
                if path_strs is None:
                    continue  # unproven txn rides the final root check
                items.append((ledger.serialize_for_tree(txn), seq - 1,
                              [Ledger.strToHash(s) for s in path_strs]))
            if items:
                MerkleVerifier(ledger.hasher).verify_leaf_inclusion_batch(
                    items, self.target_size,
                    Ledger.strToHash(self.target_root))
        except Exception:
            logger.warning("ledger %s: catchup rep from %s failed audit-"
                           "path verification — discarding the chunk",
                           self.lid, frm, exc_info=True)
            return False
        return True

    def process_catchup_rep(self, rep: CatchupRep, frm: str):
        if self.state != LeecherState.SYNCING or rep.ledgerId != self.lid:
            return
        if self.target_size is None:
            return
        if not self._verify_rep_proofs(rep, frm):
            # a proven-lying seeder is excluded from chunk assignment
            # (for every ledger) and its chunk re-requested elsewhere
            # right away instead of waiting out the retry period — but
            # only on the FIRST conviction: an already-convicted peer
            # spamming garbled reps must not amplify into a broadcast
            # re-request per rep (the retry backoff owns re-requests
            # from here on). Verified reps from convicted peers are
            # still accepted below: the all-convicted fallback depends
            # on a wrongly-blamed peer being able to redeem itself.
            if frm not in self._bad_peers:
                self._bad_peers.add(frm)
                self._record("catchup_bad_peer", lid=self.lid, peer=frm)
                self._request_missing()
            return
        added = False
        for seq_str, txn in rep.txns.items():
            seq = int(seq_str)
            if self.ledger.size < seq <= self.target_size:
                self._buffer[seq] = txn
                added = True
        if added:
            self._note_progress()
        self._try_apply()

    def _try_apply(self):
        """All txns present → replay into a shadow tree, accept only if
        the root matches the quorum-agreed target root."""
        ledger = self.ledger
        start = ledger.size + 1
        if self.target_size is None or self.target_size < start:
            self._finish()
            return
        if any(s not in self._buffer
               for s in range(start, self.target_size + 1)):
            return
        shadow = ledger.tree.copy_shadow()
        txns = [self._buffer[s] for s in range(start, self.target_size + 1)]
        # one batched device dispatch hashes the whole caught-up range
        # (TreeHasher TPU seam) before the sequential frontier merge
        leaf_hashes = ledger.hasher.hash_leaves(
            [ledger.serialize_for_tree(txn) for txn in txns])
        for leaf_hash in leaf_hashes:
            shadow._append_hash(leaf_hash)
        got_root = Ledger.hashToStr(shadow.root_hash)
        if got_root != self.target_root:
            logger.warning("catchup root mismatch on ledger %s: got %s "
                           "expected %s — discarding buffer and retrying",
                           self.lid, got_root, self.target_root)
            self._buffer.clear()
            self._request_missing()
            return
        for seq, txn in zip(range(start, self.target_size + 1), txns):
            self._on_txn(self.lid, txn)
        self._buffer.clear()
        self._finish()


class NodeLeecherService:
    """State machine over all ledgers: audit → pool → config → domain
    (reference node_leecher_service.py:21-27; audit first — it drives
    consistency of the rest, catchup.md:14-23)."""

    def __init__(self, db_manager, network, timer: TimerService,
                 quorums_source: Callable[[], Quorums],
                 on_catchup_txn: Callable[[int, dict], None],
                 on_finished: Callable[[], None],
                 config: Optional[Config] = None,
                 name: str = "?",
                 peer_ok: Callable[[str], bool] = None):
        """peer_ok(frm) → False rejects a catchup message before it can
        touch any leecher state: the Node wires current pool membership
        + its blacklist, so an unknown or blacklisted sender can neither
        vote on targets nor feed reps (it could previously pad the
        status/cons-proof quorums with fabricated senders)."""
        self._db = db_manager
        self._network = network
        self._timer = timer
        self._on_finished = on_finished
        self.name = name
        self.in_progress = False
        self._quorums = quorums_source
        self._peer_ok = peer_ok or (lambda frm: True)
        self.tracer = NullTracer(name)  # node injects the real one
        # peer → highest view reported in any status/proof this catchup
        self._view_tracker: Dict[str, int] = {}
        # peers whose reps failed proof verification (shared: lying
        # about one ledger disqualifies a seeder for all of them)
        self.bad_peers: Set[str] = set()
        self._leechers: Dict[int, LedgerLeecher] = {}
        for lid in CATCHUP_LEDGER_ORDER:
            if self._db.get_ledger(lid) is None:
                continue
            self._leechers[lid] = LedgerLeecher(
                lid, db_manager, network, timer, quorums_source,
                on_txn=on_catchup_txn, on_done=self._on_ledger_done,
                config=config, view_tracker=self._view_tracker,
                bad_peers=self.bad_peers, record=self._record,
                name=name)
        self._order = [lid for lid in CATCHUP_LEDGER_ORDER
                       if lid in self._leechers]
        self._current = 0
        network.subscribe(LedgerStatus, self._route_status)
        network.subscribe(ConsistencyProof, self._route_proof)
        network.subscribe(CatchupRep, self._route_rep)

    def _record(self, event: str, **args):
        """Recovery-lane flight-recorder instant (leecher retry/backoff
        + bad-peer events land on the node's merged timeline)."""
        self.tracer.instant(event, CAT_RECOVERY, **args)

    # ------------------------------------------------------------ routing

    def _active(self) -> Optional[LedgerLeecher]:
        if not self.in_progress or self._current >= len(self._order):
            return None
        return self._leechers[self._order[self._current]]

    def _route_status(self, msg: LedgerStatus, frm: str):
        if not self._peer_ok(frm):
            return
        leecher = self._leechers.get(msg.ledgerId)
        if leecher is not None:
            leecher.process_ledger_status(msg, frm)

    def _route_proof(self, msg: ConsistencyProof, frm: str):
        if not self._peer_ok(frm):
            return
        leecher = self._leechers.get(msg.ledgerId)
        if leecher is not None:
            leecher.process_consistency_proof(msg, frm)

    def _route_rep(self, msg: CatchupRep, frm: str):
        if not self._peer_ok(frm):
            return
        leecher = self._leechers.get(msg.ledgerId)
        if leecher is not None:
            leecher.process_catchup_rep(msg, frm)

    # ------------------------------------------------------------- drive

    def start(self):
        if self.in_progress:
            return
        self.in_progress = True
        self._current = 0
        self._view_tracker.clear()
        # a fresh catchup forgives past liars: membership may have
        # changed, and the per-rep verification re-convicts instantly
        self.bad_peers.clear()
        self._start_current()

    def pool_view_estimate(self) -> Optional[int]:
        """The pool's current view as evidenced by peers during this
        catchup: the (f+1)-th largest reported view — at least one honest
        peer has reached it. None until f+1 peers have reported. Needed
        because audit txns record each batch's ORIGINAL view, so a node
        rejoining after a view change that only re-ordered old-view
        batches cannot learn the new view from the audit ledger alone."""
        views = sorted(self._view_tracker.values(), reverse=True)
        f = self._quorums().f
        if len(views) < f + 1:
            return None
        return views[f]

    def _start_current(self):
        active = self._active()
        if active is None:
            self._finish()
            return
        active.start()

    def _on_ledger_done(self, lid: int):
        if not self.in_progress:
            return
        self._current += 1
        if self._current >= len(self._order):
            self._finish()
        else:
            self._start_current()

    def _finish(self):
        self.in_progress = False
        for leecher in self._leechers.values():
            leecher.stop()
        self._on_finished()
