"""Client request authentication — ed25519 over the signing serialization.

Reference: plenum/server/client_authn.py (`ClientAuthNr` :21, `NaclAuthNr`
:82 authenticate_multi :84, `CoreAuthNr`) + req_authenticator.py
(`ReqAuthenticator` :11).

TPU seam: `CoreAuthNr.authenticate_batch` hands the whole queue of
pending requests to the pluggable batch verifier
(plenum_tpu.crypto.batch_verifier) — thousands of signature checks become
one device dispatch, the north-star path. Single requests fall through
the same provider's scalar floor.
"""
from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Sequence

from plenum_tpu.common.exceptions import (
    CouldNotAuthenticate, InsufficientCorrectSignatures,
    InsufficientSignatures, InvalidSignature)
from plenum_tpu.common.request import Request
from plenum_tpu.common.serializers.base58 import b58decode
from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing
from plenum_tpu.crypto.batch_verifier import create_verifier
from plenum_tpu.crypto.signer import verkey_from_identifier

logger = logging.getLogger(__name__)


class ClientAuthNr(ABC):
    @abstractmethod
    def authenticate(self, req: Request) -> List[str]:
        """→ identifiers whose signatures verified; raises on failure."""

    @abstractmethod
    def addIdr(self, identifier: str, verkey: str, role=None): ...

    @abstractmethod
    def getVerkey(self, identifier: str) -> Optional[str]: ...


class CoreAuthNr(ClientAuthNr):
    def __init__(self, verkey_provider=None, verifier=None,
                 prescreen=None):
        """verkey_provider(identifier) → verkey str or None (state-backed
        in the node; local dict fallback for tests)."""
        self._verkey_provider = verkey_provider
        self._local: Dict[str, str] = {}
        self._verifier = verifier or create_verifier("adaptive")
        self._prescreen = prescreen

    def set_prescreen(self, cache) -> None:
        """Install an advisory verdict cache (the pipeline's
        PrescreenCache): ``cache.check((ser, sig64, vk32))`` is True
        ONLY for a signature already verified somewhere — a hit skips
        the scalar verify for that item; a miss — or no pre-screen —
        takes the full verifier path, so outcomes are byte-identical
        either way (positive-only filter, never an authority). The
        authenticator also WARMS the cache on every successful verify,
        so the 8 propagate copies of a request the pool relays cost
        one verification, not eight."""
        self._prescreen = cache

    # ------------------------------------------------------------- keys

    def addIdr(self, identifier: str, verkey: str, role=None):
        self._local[identifier] = verkey

    def getVerkey(self, identifier: str) -> Optional[str]:
        if identifier in self._local:
            return self._local[identifier]
        if self._verkey_provider is not None:
            return self._verkey_provider(identifier)
        return None

    # (identifier, verkey_str) → raw 32/64 bytes; keyed on BOTH so a
    # rotated verkey can never serve a stale raw key — the conversion
    # is deterministic, only the lookup result can change
    _raw_cache: Dict[tuple, bytes] = {}

    def _raw_verkey(self, identifier: str) -> bytes:
        verkey = self.getVerkey(identifier)
        cache_key = (identifier, verkey)
        raw = self._raw_cache.get(cache_key)
        if raw is None:
            raw = verkey_from_identifier(identifier, verkey)
            if len(self._raw_cache) > 8192:
                self._raw_cache.clear()
            self._raw_cache[cache_key] = raw
        return raw

    # ----------------------------------------------------------- single

    def authenticate(self, req: Request) -> List[str]:
        items, idrs = self._verify_items(req)
        results = self._verify_batch_prescreened(items)
        return self._conclude(req, idrs, results)

    def _verify_batch_prescreened(self, items) -> List[bool]:
        """verify_batch with cached-positive short-circuit: items the
        pre-screen already verified (exact (ser, sig, vk) triple) skip
        the scalar verify; everything else verifies normally."""
        pre = self._prescreen
        if pre is None:
            return self._verifier.verify_batch(items)
        misses = [i for i, it in enumerate(items) if not pre.check(it)]
        if not misses:
            return [True] * len(items)
        verified = self._verifier.verify_batch(
            [items[i] for i in misses])
        results = [True] * len(items)
        for i, ok in zip(misses, verified):
            results[i] = bool(ok)
            if ok:
                pre.add(*items[i])
        return results

    # ------------------------------------------------------------ batch

    def authenticate_batch(self, reqs: Sequence[Request]
                           ) -> List[Optional[List[str]]]:
        """Authenticate many requests in ONE device dispatch. Returns, per
        request, the verified identifier list or None if auth failed."""
        return self.conclude_batch(self.dispatch_batch(reqs))

    def dispatch_batch(self, reqs: Sequence[Request]):
        """Phase 1 (non-blocking): pack every signature on every request
        into one device dispatch and return a pending handle. The prod
        loop overlaps consensus work / other nodes\' batches with the
        device round trip and calls conclude_batch later (SURVEY.md §7
        async-dispatch backpressure design).."""
        all_items, spans, idrs_per_req = [], [], []
        prep_errors: List[Optional[Exception]] = []
        for req in reqs:
            try:
                items, idrs = self._verify_items(req)
                prep_errors.append(None)
            except Exception as e:
                items, idrs = [], []
                prep_errors.append(e)
            spans.append((len(all_items), len(items)))
            idrs_per_req.append(idrs)
            all_items.extend(items)
        pending = self._verifier.dispatch(all_items) if all_items else None
        return (list(reqs), spans, idrs_per_req, prep_errors, pending,
                all_items)

    def flush(self) -> None:
        """Start any coalesced device launch now (CoalescingVerifierHub);
        no-op for providers without a coalescing window. A networked
        node calls this right after its tick's dispatch — nothing else
        co-resident will deepen the generation, and without the flush a
        hub pending's ready() could never turn true."""
        fn = getattr(self._verifier, "flush", None)
        if fn is not None:
            fn()

    def batch_ready(self, handle) -> bool:
        """Non-blocking: True when conclude_batch will not block on the
        device/daemon (the prod loop polls this to overlap the round
        trip with consensus work)."""
        pending = handle[4]
        if pending is None:
            return True
        r = getattr(pending, "ready", None)
        return bool(r()) if r is not None else True

    def conclude_batch(self, handle) -> List[Optional[List[str]]]:
        """Phase 2 (blocking): harvest the device results."""
        reqs, spans, idrs_per_req, prep_errors, pending, all_items = handle
        results = pending.collect() if pending is not None else []
        if self._prescreen is not None:
            # warm the verdict cache from the intake verifies: the
            # propagate copies of these requests then pre-screen clean
            for item, ok in zip(all_items, results):
                if ok:
                    self._prescreen.add(*item)
        out: List[Optional[List[str]]] = []
        for req, (start, count), idrs, err in zip(reqs, spans, idrs_per_req,
                                                  prep_errors):
            if err is not None:
                out.append(None)
                continue
            try:
                out.append(self._conclude(
                    req, idrs, results[start:start + count]))
            except Exception:
                out.append(None)
        return out

    # ---------------------------------------------------------- internal

    def _verify_items(self, req: Request):
        """→ ([(msg_bytes, sig64, vk32)], [identifier]) for every
        signature on the request."""
        sigs: Dict[str, str] = {}
        if req.signatures:
            sigs.update(req.signatures)
        if req.signature:
            if req.identifier is None:
                raise CouldNotAuthenticate(
                    None, req.reqId, "signature without identifier")
            sigs[req.identifier] = req.signature
        if not sigs:
            raise InsufficientSignatures(0, 1)
        items, idrs = [], []
        for idr, sig in sorted(sigs.items()):
            try:
                sig_raw = b58decode(sig)
            except Exception:
                raise InvalidSignature(
                    idr, req.reqId, "malformed signature from {}".format(idr))
            try:
                vk = self._raw_verkey(idr)
            except Exception:
                vk = None
            if vk is None:
                raise CouldNotAuthenticate(
                    idr, req.reqId, "no verkey for {}".format(idr))
            if idr == req.identifier and req._signing_ser is not None:
                # canonical bytes already built by the C intake pass
                ser = req._signing_ser
            else:
                ser = serialize_msg_for_signing(req.signingPayloadState(idr))
            items.append((ser, sig_raw, vk))
            idrs.append(idr)
        return items, idrs

    @staticmethod
    def _conclude(req: Request, idrs: List[str],
                  results: Sequence[bool]) -> List[str]:
        ok = [i for i, good in zip(idrs, results) if good]
        if len(ok) != len(idrs):
            raise InsufficientCorrectSignatures(len(ok), len(idrs))
        return ok


class ReqAuthenticator:
    """Registry of authenticators (reference req_authenticator.py:11)."""

    def __init__(self):
        self._authenticators: List[ClientAuthNr] = []

    def register_authenticator(self, authnr: ClientAuthNr):
        self._authenticators.append(authnr)

    def authenticate(self, req: Request) -> List[str]:
        identifiers = []
        for a in self._authenticators:
            identifiers.extend(a.authenticate(req))
        return identifiers

    @property
    def core_authenticator(self) -> Optional[CoreAuthNr]:
        for a in self._authenticators:
            if isinstance(a, CoreAuthNr):
                return a
        return None
