"""Persist the last PrePrepare a BACKUP primary sent, restore on
restart.

Reference: plenum/server/last_sent_pp_store_helper.py:10. The master
primary recovers its 3PC position through catchup (the audit ledger),
but backup instances carry no ledger — a restarted backup primary
would reuse pp_seq_nos from 1 and be ignored by peers until a view
change. Persisting (inst_id, view_no, pp_seq_no) in the node status DB
lets it resume where it left off.
"""
from __future__ import annotations

import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

LAST_SENT_PP_KEY = b"lastSentPrePrepare"


class LastSentPpStoreHelper:
    def __init__(self, node_status_db):
        self._db = node_status_db

    def store_last_sent(self, inst_id: int, view_no: int,
                        pp_seq_no: int) -> None:
        self._db.put(LAST_SENT_PP_KEY,
                     json.dumps([inst_id, view_no, pp_seq_no]).encode())

    def erase_last_sent(self) -> None:
        try:
            self._db.remove(LAST_SENT_PP_KEY)
        except KeyError:
            pass

    def load_last_sent(self) -> Optional[Tuple[int, int, int]]:
        try:
            raw = self._db.get(LAST_SENT_PP_KEY)
        except KeyError:
            return None
        try:
            inst_id, view_no, pp_seq_no = json.loads(raw.decode())
            return int(inst_id), int(view_no), int(pp_seq_no)
        except (ValueError, TypeError):
            logger.warning("malformed lastSentPrePrepare record %r", raw)
            return None

    def try_restore(self, node) -> bool:
        """Restore a backup primary's 3PC position (reference
        try_restore_last_sent_pp_seq_no + _can_restore conditions:
        instance exists, this node is its primary, never the master).

        Must run AFTER the master adopted its view from the audit
        ledger: the stored view is compared against the MASTER's view
        (backups are constructed at view 0 and only aligned here), and
        the restore mirrors the reference's _restore_last_stored —
        lastPrePrepareSeqNo AND last_ordered_3pc AND watermarks — else
        the in-flight gate and strict-sequential ordering stall the
        instance right after restart."""
        stored = self.load_last_sent()
        if stored is None:
            return False
        inst_id, view_no, pp_seq_no = stored
        if inst_id == 0:
            logger.warning("%s: ignoring stored %s — the master primary "
                           "restores via catchup", node.name, stored)
            return False
        if inst_id not in [r.data.inst_id for r in node.replicas]:
            logger.info("%s: ignoring stored %s — no instance %d",
                        node.name, stored, inst_id)
            return False
        master_view = node.view_no
        if view_no != master_view:
            logger.info("%s: ignoring stored %s — pool view is %d",
                        node.name, stored, master_view)
            return False
        replica = node.replicas[inst_id]
        # align the backup (built at view 0) with the adopted view so
        # the primary check runs against the RIGHT selection
        replica.reset_for_view(master_view)
        if replica.data.primary_name != node.name:
            logger.info("%s: ignoring stored %s — not primary of "
                        "instance %d", node.name, stored, inst_id)
            return False
        replica.ordering.lastPrePrepareSeqNo = pp_seq_no
        replica.ordering._last_applied_seq = pp_seq_no
        replica.data.pp_seq_no = pp_seq_no
        replica.data.last_ordered_3pc = (master_view, pp_seq_no)
        replica.checkpointer.caught_up_till_3pc((master_view, pp_seq_no))
        logger.info("%s: restored backup instance %d to pp_seq_no %d",
                    node.name, inst_id, pp_seq_no)
        return True
