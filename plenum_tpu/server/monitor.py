"""Monitor — performance watchdog and primary-failure detection.

Reference: plenum/server/monitor.py (Monitor :136, RequestTimeTracker :30,
isMasterDegraded :425, instance_throughput_ratio :456), pluggable
throughput strategies (plenum/common/throughput_measurements.py: EMA
:25, revival-spike-resistant :99), and
plenum/server/consensus/monitoring/primary_connection_monitor_service.py
(primary disconnected > ToleratePrimaryDisconnection → vote view change).

RBFT's core idea: backup protocol instances exist only to benchmark the
master — if the master's throughput ratio vs the best backup drops below
Δ, the master primary is assumed malicious/slow and a view change fires.
With a single instance (this round), degradation falls back to latency:
requests ordered too slowly (> Λ) trigger the same vote.
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Callable, Dict, List, Optional

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import (
    PrimaryDisconnected, VoteForViewChange)
from plenum_tpu.runtime.bus import ExternalBus
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService

logger = logging.getLogger(__name__)


class EMAThroughputMeasurement:
    """Exponential-moving-average req/s (reference
    throughput_measurements.py:25)."""

    def __init__(self, window_size: int = 15, min_cnt: int = 16,
                 first_ts: float = 0.0):
        self.window_size = window_size
        self.alpha = 2 / (min_cnt + 1)
        self.throughput = 0.0
        self.reqs_in_window = 0
        self.window_start_ts = first_ts

    def add_request(self, ts: float):
        self._update_time(ts)
        self.reqs_in_window += 1

    def add_requests(self, ts: float, n: int):
        """Bulk variant: one window roll for a whole ordered batch."""
        self._update_time(ts)
        self.reqs_in_window += n

    def get_throughput(self, ts: float) -> Optional[float]:
        self._update_time(ts)
        return self.throughput

    def _update_time(self, ts: float):
        while ts >= self.window_start_ts + self.window_size:
            rate = self.reqs_in_window / self.window_size
            self.throughput = (self.alpha * rate
                               + (1 - self.alpha) * self.throughput)
            self.window_start_ts += self.window_size
            self.reqs_in_window = 0


class RevivalSpikeResistantEMAThroughputMeasurement(EMAThroughputMeasurement):
    """Ignores the throughput spike right after an idle period (reference
    :99 — a revived node bursts through its backlog and would look
    artificially fast)."""

    def __init__(self, window_size: int = 15, min_cnt: int = 16,
                 first_ts: float = 0.0):
        super().__init__(window_size, min_cnt, first_ts)
        self._idle_windows = 0
        self._suppress_windows = 0

    def _update_time(self, ts: float):
        while ts >= self.window_start_ts + self.window_size:
            rate = self.reqs_in_window / self.window_size
            if self.reqs_in_window == 0:
                self._idle_windows += 1
            else:
                if self._idle_windows >= 2:
                    # first active windows after idling: don't learn the
                    # spike
                    self._suppress_windows = 2
                self._idle_windows = 0
            if self._suppress_windows > 0:
                self._suppress_windows -= 1
            else:
                self.throughput = (self.alpha * rate
                                   + (1 - self.alpha) * self.throughput)
            self.window_start_ts += self.window_size
            self.reqs_in_window = 0


class RequestTimeTracker:
    """digest → submission time of requests awaiting ordering (reference
    monitor.py:30)."""

    def __init__(self):
        self._started: Dict[str, float] = {}

    def start(self, digest: str, ts: float):
        self._started.setdefault(digest, ts)

    def order(self, digest: str, ts: float) -> Optional[float]:
        t0 = self._started.pop(digest, None)
        return None if t0 is None else ts - t0

    def peek(self, digest: str, ts: float) -> Optional[float]:
        """Latency if ordered at `ts`, WITHOUT consuming the entry —
        backup instances observe latency but only the master's ordering
        completes a request."""
        t0 = self._started.get(digest)
        return None if t0 is None else ts - t0

    def unordered(self, now: float) -> List[float]:
        return [now - t0 for t0 in self._started.values()]

    def reset(self):
        self._started.clear()


class ClientLatencyMeasurement:
    """Per-client EMA latency (reference latency_measurements.py:17
    EMALatencyMeasurementForEachClient + MedianHighStrategy): one EMA
    per client identifier; the pool-level figure is the high median
    across clients so a single fast client can't mask slow service to
    the rest."""

    MAX_CLIENTS = 1000  # LRU bound: identifiers are client-chosen, so
    # an unbounded map is an attacker-controlled allocation

    def __init__(self, min_latency_count: int = 10):
        from collections import OrderedDict
        self.min_latency_count = min_latency_count
        self.alpha = 1.0 / (min_latency_count + 1)
        # identifier → (ordered_count, ema_latency_seconds), LRU-ordered
        self.avg_latencies: "OrderedDict[str, tuple]" = OrderedDict()
        self.total_reqs = 0

    def add_duration(self, identifier: str, duration: float):
        cnt, ema = self.avg_latencies.get(identifier, (0, 0.0))
        self.avg_latencies[identifier] = (
            cnt + 1, ema * (1 - self.alpha) + duration * self.alpha)
        self.avg_latencies.move_to_end(identifier)
        while len(self.avg_latencies) > self.MAX_CLIENTS:
            self.avg_latencies.popitem(last=False)
        self.total_reqs += 1

    def get_avg_latency(self) -> Optional[float]:
        if self.total_reqs < self.min_latency_count:
            return None
        lats = sorted(ema for _, ema in self.avg_latencies.values())
        return lats[len(lats) // 2]  # high median

    # display bound, not a consensus tunable — the 100 here only shares
    # a value with CHK_FREQ by coincidence
    def per_client(self, limit: int = 100  # plenum-lint: disable=PT005
                   ) -> Dict[str, dict]:
        """Snapshot of the busiest `limit` clients (full map stays
        internal — validator-info dumps must stay bounded)."""
        busiest = sorted(self.avg_latencies.items(),
                         key=lambda kv: -kv[1][0])[:limit]
        return {ident: {"count": cnt, "avg": round(ema, 6)}
                for ident, (cnt, ema) in busiest}

    def reset(self):
        self.avg_latencies.clear()
        self.total_reqs = 0


class Monitor:
    def __init__(self, name: str, timer: TimerService, bus,
                 config: Optional[Config] = None,
                 num_instances_source: Callable[[], int] = lambda: 1):
        self.name = name
        self._timer = timer
        self._bus = bus
        self.config = config or Config()
        self._num_instances = num_instances_source
        # per-instance throughput, instance 0 = master
        self.throughputs: Dict[int, EMAThroughputMeasurement] = {}
        self.request_tracker = RequestTimeTracker()
        self.client_latencies = ClientLatencyMeasurement(
            self.config.MIN_LATENCY_COUNT)
        self.latencies = deque(maxlen=50)
        # per-backup-instance observed latencies for the reference's
        # Ω check (monitor.py:425-490 isMasterAvgReqLatencyTooHigh):
        # a master that keeps ordering — slowly — never trips the
        # throughput ratio, but backups ordering the same requests much
        # faster expose it here
        self.backup_latencies: Dict[int, deque] = {}
        self.total_ordered = 0
        self._warm = False
        from plenum_tpu.utils.metrics import NullMetricsCollector
        self.metrics = NullMetricsCollector()  # node injects the real one

    def _throughput(self, inst_id: int) -> EMAThroughputMeasurement:
        if inst_id not in self.throughputs:
            self.throughputs[inst_id] = \
                RevivalSpikeResistantEMAThroughputMeasurement(
                    window_size=self.config.ThroughputWindowSize,
                    first_ts=self._timer.get_current_time())
        return self.throughputs[inst_id]

    # ------------------------------------------------------------ inputs

    def request_received(self, digest: str):
        self.request_tracker.start(digest,
                                   self._timer.get_current_time())

    def requests_ordered_bulk(self, digest_idr_pairs, inst_id: int = 0):
        """request_ordered for a whole committed batch in one call:
        one clock read, one throughput-window roll, hoisted locals —
        the per-digest variant was a top-10 site on the ordering money
        path (it runs once per request per instance)."""
        now = self._timer.get_current_time()
        self._throughput(inst_id).add_requests(now, len(digest_idr_pairs))
        if inst_id != 0:
            peek = self.request_tracker.peek
            lat_q = self.backup_latencies.setdefault(
                inst_id, deque(maxlen=50))
            for digest, _idr in digest_idr_pairs:
                lat = peek(digest, now)
                if lat is not None:
                    lat_q.append(lat)
            return
        order = self.request_tracker.order
        latencies = self.latencies
        add_dur = self.client_latencies.add_duration
        ordered = 0
        for digest, identifier in digest_idr_pairs:
            latency = order(digest, now)
            if latency is not None:
                latencies.append(latency)
                if identifier:
                    add_dur(identifier, latency)
                ordered += 1
        self.total_ordered += ordered
        self._warm = self._warm or \
            self.total_ordered >= self.config.MIN_LATENCY_COUNT

    def request_ordered(self, digest: str, inst_id: int = 0,
                        identifier: str = None):
        now = self._timer.get_current_time()
        self._throughput(inst_id).add_request(now)
        if inst_id != 0:
            # backups feed the throughput comparison and the Ω latency
            # comparison; the tracker entry must survive (peek, not
            # order) until the MASTER orders it
            lat = self.request_tracker.peek(digest, now)
            if lat is not None:
                self.backup_latencies.setdefault(
                    inst_id, deque(maxlen=50)).append(lat)
            return
        latency = self.request_tracker.order(digest, now)
        if latency is not None:
            self.latencies.append(latency)
            if identifier:
                self.client_latencies.add_duration(identifier, latency)
            self.total_ordered += 1
            self._warm = self._warm or \
                self.total_ordered >= self.config.MIN_LATENCY_COUNT

    def reset(self):
        """View change happened: measurements restart."""
        self.throughputs.clear()
        self.request_tracker.reset()
        self.latencies.clear()
        self.backup_latencies.clear()
        self.client_latencies.reset()

    # --------------------------------------------------------- judgments

    def instance_throughput(self, inst_id: int) -> Optional[float]:
        """Current EMA throughput of one instance (None = no data)."""
        t = self.throughputs.get(inst_id)
        if t is None:
            return None
        return t.get_throughput(self._timer.get_current_time())

    def instance_throughput_ratio(self, inst_id: int = 0) -> Optional[float]:
        """master throughput / best backup throughput (reference :456)."""
        now = self._timer.get_current_time()
        others = [t.get_throughput(now)
                  for i, t in self.throughputs.items() if i != inst_id]
        others = [t for t in others if t]
        if not others:
            return None
        mine = self._throughput(inst_id).get_throughput(now) or 0.0
        return mine / max(others)

    def master_latency_excess(self) -> Optional[float]:
        """Master avg latency minus the best backup's avg latency —
        the reference's Ω divergence (isMasterAvgReqLatencyTooHigh,
        monitor.py:466-490). None until BOTH sides have at least
        MIN_LATENCY_COUNT samples — a single fast backup observation
        against a backlogged master's average must not read as
        divergence (the reference gates both sides the same way)."""
        min_n = self.config.MIN_LATENCY_COUNT
        backup_avgs = [sum(d) / len(d)
                       for d in self.backup_latencies.values()
                       if len(d) >= min_n]
        if not backup_avgs or len(self.latencies) < min_n:
            return None
        master_avg = sum(self.latencies) / len(self.latencies)
        return master_avg - min(backup_avgs)

    def is_master_degraded(self) -> bool:
        """RBFT check (reference isMasterDegraded :425): throughput
        ratio below Δ, master-vs-backup avg latency diverging beyond Ω,
        or (single-instance fallback) requests stuck unordered beyond
        Λ."""
        from plenum_tpu.utils.metrics import MetricsName
        with self.metrics.measure_time(MetricsName.MONITOR_CHECK_TIME):
            return self._is_master_degraded()

    def _is_master_degraded(self) -> bool:
        from plenum_tpu.utils.metrics import MetricsName
        ratio = self.instance_throughput_ratio(0)
        mine = self.instance_throughput(0)
        if mine is not None:
            self.metrics.add_event(MetricsName.MASTER_THROUGHPUT, mine)
        lat = self.avg_latency()
        if lat is not None:
            self.metrics.add_event(MetricsName.MASTER_AVG_LATENCY, lat)
        if ratio is not None and ratio < self.config.DELTA:
            return True
        excess = self.master_latency_excess()
        if excess is not None and self._warm \
                and excess > self.config.OMEGA:
            return True
        now = self._timer.get_current_time()
        stuck = [age for age in self.request_tracker.unordered(now)
                 if age > self.config.LAMBDA]
        return bool(stuck)

    def avg_latency(self) -> Optional[float]:
        if not self.latencies:
            return None
        return sum(self.latencies) / len(self.latencies)


class PrimaryConnectionMonitorService:
    """Votes for a view change when the master primary stays disconnected
    longer than ToleratePrimaryDisconnection (reference
    primary_connection_monitor_service.py)."""

    def __init__(self, data, timer: TimerService, bus,
                 network: ExternalBus, config: Optional[Config] = None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self._primary_disconnected_at: Optional[float] = None
        network.subscribe(ExternalBus.Connected, self._connection_changed)
        network.subscribe(ExternalBus.Disconnected, self._connection_changed)
        # events alone miss the join-while-primary-dead case: a node
        # that starts (or changes views) with the primary already absent
        # never receives a Disconnected event — poll current state too
        self._check_timer = RepeatingTimer(
            timer, max(1.0, self._config.ToleratePrimaryDisconnection / 4),
            self._check)

    def _primary_absent(self) -> bool:
        primary = self._data.primary_name
        return (primary is not None
                and primary != self._data.name
                and primary not in self._network.connecteds)

    def stop(self):
        self._check_timer.stop()

    def _connection_changed(self, msg, frm: str):
        if frm != self._data.primary_name:
            return
        if isinstance(msg, ExternalBus.Disconnected):
            self._primary_disconnected_at = self._timer.get_current_time()
            self._bus.send(PrimaryDisconnected(inst_id=self._data.inst_id))
        else:
            self._primary_disconnected_at = None

    def _check(self):
        if self._primary_disconnected_at is None:
            if self._primary_absent():
                # primary was already gone when we (re)started — begin
                # the tolerance clock now
                self._primary_disconnected_at = \
                    self._timer.get_current_time()
            return
        if not self._primary_absent():
            self._primary_disconnected_at = None
            return
        if self._data.is_primary:
            return
        elapsed = self._timer.get_current_time() \
            - self._primary_disconnected_at
        if elapsed >= self._config.ToleratePrimaryDisconnection:
            logger.info("%s primary %s disconnected for %.0fs — voting "
                        "view change", self._data.name,
                        self._data.primary_name, elapsed)
            self._primary_disconnected_at = self._timer.get_current_time()
            self._bus.send(VoteForViewChange(
                suspicion="PRIMARY_DISCONNECTED"))
