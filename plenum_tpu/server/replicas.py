"""Replicas — the RBFT redundant-protocol-instance collection.

Reference: plenum/server/replicas.py:19 (Replicas, add_replica :32,
service_inboxes :100), plenum/server/node.py:1248 (checkInstances /
adjustReplicas), plenum/server/backup_instance_faulty_processor.py.

RBFT's defining mechanism: beside the master instance (inst 0) the node
runs f backup protocol instances ordering the SAME finalized requests
under DIFFERENT primaries. Backups never execute — their whole purpose
is to benchmark the master: if the master's throughput falls below Δ ×
the best backup's, the master primary is presumed slow/malicious and the
Monitor fires a view change (the ratio path, reference monitor.py:425).

All instances share the node's ExternalBus; 3PC/checkpoint/MessageReq
messages carry instId and each service discards other instances'
traffic, so no explicit routing layer is needed. On the master's
NewViewAccepted backups restart clean in the new view with their rotated
primaries.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.internal_messages import NewViewAccepted
from plenum_tpu.common.messages.node_messages import Ordered
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.runtime.timer import TimerService

logger = logging.getLogger(__name__)


def num_instances_for(n_validators: int) -> int:
    """f + 1 protocol instances (reference plenum/common/util.py
    getMaxFailures + replicas growth rule)."""
    f = (n_validators - 1) // 3
    return f + 1


class Replicas:
    def __init__(self, node_name: str, validators: List[str],
                 timer: TimerService, network,
                 master: ReplicaService,
                 config: Optional[Config] = None,
                 on_backup_ordered: Callable[[Ordered], None] = None,
                 on_backup_pp_sent: Callable[[int, int, int], None] = None):
        self._node_name = node_name
        self._validators = list(validators)
        self._timer = timer
        self._network = network
        self.config = config or Config()
        self._on_backup_ordered = on_backup_ordered or (lambda o: None)
        self._on_backup_pp_sent = on_backup_pp_sent
        self._suspicion_handlers: List[Callable] = []
        self._outbox = None
        self._replicas: Dict[int, ReplicaService] = {0: master}
        master.internal_bus.subscribe(NewViewAccepted,
                                      self._on_master_new_view)
        self.adjust_replicas()

    # ------------------------------------------------------- collection

    @property
    def master(self) -> ReplicaService:
        return self._replicas[0]

    @property
    def num_instances(self) -> int:
        return len(self._replicas)

    @property
    def backup_ids(self) -> List[int]:
        return sorted(i for i in self._replicas if i != 0)

    def __iter__(self):
        return iter(self._replicas.values())

    def __getitem__(self, inst_id: int) -> ReplicaService:
        return self._replicas[inst_id]

    def adjust_replicas(self, validators: Optional[List[str]] = None) -> int:
        """Grow/shrink backups to f+1 total instances (reference
        node.py:1260 adjustReplicas). → delta added (negative=removed)."""
        if validators is not None:
            self._validators = list(validators)
        wanted = num_instances_for(len(self._validators))
        delta = 0
        while self.num_instances < wanted:
            self._add_backup(max(self._replicas) + 1)
            delta += 1
        while self.num_instances > wanted:
            self.remove_backup(max(self._replicas))
            delta -= 1
        return delta

    def _add_backup(self, inst_id: int):
        replica = ReplicaService(
            self._node_name, self._validators, self._timer, self._network,
            inst_id=inst_id, is_master=False, config=self.config)
        # align with the master's current view
        replica.reset_for_view(self.master.view_no)
        replica.internal_bus.subscribe(Ordered, self._on_backup_ordered)
        if self._on_backup_pp_sent is not None:
            replica.ordering.on_pp_sent = (
                lambda view_no, pp_seq_no, iid=inst_id:
                self._on_backup_pp_sent(iid, view_no, pp_seq_no))
        from plenum_tpu.common.messages.internal_messages import (
            RaisedSuspicion)
        for handler in self._suspicion_handlers:
            replica.internal_bus.subscribe(RaisedSuspicion, handler)
        replica.ordering.outbox = self._outbox
        self._replicas[inst_id] = replica
        logger.info("%s: added backup instance %d (primary %s)",
                    self._node_name, inst_id, replica.data.primary_name)

    def remove_backup(self, inst_id: int):
        """Remove a (faulty) backup instance (reference
        replicas.py remove_replica; master is never removable)."""
        if inst_id == 0:
            raise ValueError("cannot remove the master instance")
        replica = self._replicas.pop(inst_id, None)
        if replica is not None:
            replica.stasher.unsubscribe_all()
            replica.message_req.stop()
            logger.info("%s: removed backup instance %d",
                        self._node_name, inst_id)

    def subscribe_suspicions(self, handler: Callable) -> None:
        """Route RaisedSuspicion from EVERY protocol instance (master +
        current and future backups) to the node-level reporter."""
        from plenum_tpu.common.messages.internal_messages import (
            RaisedSuspicion)
        self._suspicion_handlers.append(handler)
        for replica in self._replicas.values():
            replica.internal_bus.subscribe(RaisedSuspicion, handler)

    # --------------------------------------------------------- fan-out

    def set_outbox(self, outbox) -> None:
        """Attach one node-wide coalescing 3PC outbox to every protocol
        instance — current AND future backups (all instances' broadcast
        votes ride the same per-tick THREE_PC_BATCH)."""
        self._outbox = outbox
        for replica in self._replicas.values():
            replica.ordering.outbox = outbox

    def get(self, inst_id: int) -> Optional[ReplicaService]:
        """Instance by id, None when this node runs fewer instances than
        the sender (membership skew) — batch routing drops those."""
        return self._replicas.get(inst_id)

    def submit_request(self, digest: str, ledger_id: int = 1):
        for replica in self._replicas.values():
            replica.submit_request(digest, ledger_id)

    def submit_requests(self, digests, ledger_id: int = 1):
        """One finalized propagate batch into every instance's proposal
        queue — the stash replay inside runs once per (instance, batch)
        instead of once per (instance, request)."""
        for replica in self._replicas.values():
            replica.submit_requests(digests, ledger_id)

    def service(self) -> int:
        return sum(r.service() for r in list(self._replicas.values()))

    def _on_master_new_view(self, msg: NewViewAccepted):
        for inst_id in self.backup_ids:
            self._replicas[inst_id].reset_for_view(self.master.view_no)


class BackupInstanceFaultyProcessor:
    """Detects dead/unproductive backup instances and removes them
    (reference plenum/server/backup_instance_faulty_processor.py;
    REPLICAS_REMOVING_WITH_DEGRADATION='local' strategy: a backup whose
    throughput stays at zero while the master makes progress is removed
    locally — no pool vote needed since backups carry no state)."""

    def __init__(self, replicas: Replicas, monitor,
                 config: Optional[Config] = None):
        self._replicas = replicas
        self._monitor = monitor
        self.config = config or Config()
        self._strikes: Dict[int, int] = {}
        self.removed: List[int] = []

    def check(self):
        if self.config.REPLICAS_REMOVING_WITH_DEGRADATION != "local":
            return
        now_tput = {}
        for inst_id in list(self._replicas.backup_ids):
            tput = self._monitor.instance_throughput(inst_id)
            master_tput = self._monitor.instance_throughput(0)
            if master_tput and not tput:
                self._strikes[inst_id] = self._strikes.get(inst_id, 0) + 1
            else:
                self._strikes.pop(inst_id, None)
            now_tput[inst_id] = tput
        for inst_id, strikes in list(self._strikes.items()):
            if strikes >= 3:
                logger.warning("backup instance %d faulty (no throughput "
                               "for %d checks) — removing", inst_id, strikes)
                self._replicas.remove_backup(inst_id)
                self.removed.append(inst_id)
                self._strikes.pop(inst_id)
