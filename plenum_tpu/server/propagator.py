"""Propagator — client-request propagation and finalization.

Reference: plenum/server/propagator.py — `Requests` (:62, digest →
request + votes), `Propagator` (:195): on a new client request, broadcast
PROPAGATE; once f+1 nodes propagated identical requests the request is
"finalised" and forwarded to the ordering queues.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set

import msgpack

from plenum_tpu.common.messages.node_messages import (
    FlatBatch, Propagate, PropagateBatch)
from plenum_tpu.common.request import Request
from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.common.serializers.serializers import MsgPackSerializer
from plenum_tpu.consensus.quorums import Quorums
from plenum_tpu.observability.tracing import CAT_PROPAGATE, NullTracer
from plenum_tpu.observability.telemetry import TM, get_seam_hub
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector

_wire_serializer = MsgPackSerializer()

logger = logging.getLogger(__name__)


def _payload_size(payload: dict) -> int:
    """Serialized size estimate for batch budgeting (exact when the C
    canonical packer is available; real msgpack size otherwise — a flat
    guess would under-count multi-KB ATTRIB raws, letting a batch exceed
    the transport frame limit and be dropped wholesale)."""
    if _fp is not None:
        try:
            return len(_fp.canonical_msgpack(payload)) + 16
        except TypeError:
            pass
    try:
        return len(msgpack.packb(payload, use_bin_type=True)) + 16
    except Exception:
        # unpackable oddity: assume the worst entry the budget accepts
        # 40 of rather than dropping the propagate entirely
        return 3 * 1024


def _strict_deep_eq_py(a, b) -> bool:
    """Deep equality that also requires identical types at every node —
    digest-faithful for the canonical serializers (which encode True,
    1, and 1.0 differently while Python `==` conflates them)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):
        if len(a) != len(b):
            return False
        for k, v in a.items():
            if k not in b or not _strict_deep_eq_py(v, b[k]):
                return False
        return True
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(
            _strict_deep_eq_py(x, y) for x, y in zip(a, b))
    return a == b


from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")
if _fp is not None:
    def _strict_deep_eq(a, b, _c=_fp.deep_eq):
        try:
            return _c(a, b)
        except TypeError:  # structure too deep for the C guard
            return _strict_deep_eq_py(a, b)
else:
    _strict_deep_eq = _strict_deep_eq_py


class ReqState:
    def __init__(self, request: Request):
        self.request = request
        self.propagates: Set[str] = set()
        self.finalised = False
        self.forwarded = False
        self.executed = False
        self.payload = None      # canonical as_dict(), built on first use


class Requests(dict):
    """digest → ReqState (reference propagator.py:62).

    A (identifier, reqId) side-index lets the propagate path recognise a
    request it already holds WITHOUT recomputing the digest — computing
    the key costs a canonical serialization + sha256, and with n nodes
    gossiping every request arrives n-1 times (the dominant per-request
    cost at 25 nodes). On an index hit the incoming payload is compared
    to the stored request's dict (plain dict equality, no hashing); a
    mismatch (byzantine reuse of a reqId with different content) falls
    back to the full digest path."""

    def __init__(self):
        super().__init__()
        # (identifier, reqId) → ReqState, straight to the state object:
        # the propagate hot path must not pay a second dict hop through
        # the digest
        self._by_ref: dict = {}

    def add(self, req: Request) -> ReqState:
        key = req.key
        state = self.get(key)
        if state is None:
            state = self[key] = ReqState(req)
        # first writer wins: a later same-(identifier, reqId) variant
        # must not hijack the fast-path index and starve the request
        # that is already collecting votes — but a still-live state
        # DOES re-claim a slot vacated by free(), or every later gossip
        # copy would pay the full digest + auth path the index avoids
        self._by_ref.setdefault((req.identifier, req.reqId), state)
        return state

    def ref_state(self, payload: dict) -> Optional[ReqState]:
        """Raw (identifier, reqId) index hit WITHOUT the deep-equality
        check — only valid for decisions that don't depend on payload
        content (e.g. 'already forwarded, nothing to do')."""
        return self._by_ref.get((payload.get("identifier"),
                                 payload.get("reqId")))

    def lookup_state(self, payload: dict) -> Optional[ReqState]:
        """Cheap pre-digest lookup: the stored ReqState if `payload` is
        bit-for-bit the request we already hold, else None. Equality is
        TYPE-STRICT deep comparison — the digest's canonical
        serialization distinguishes True/1/1.0, so plain dict equality
        (which conflates them) would let a byzantine re-gossip count as
        a vote for the original digest; any mismatch falls back to the
        full digest path."""
        state = self._by_ref.get((payload.get("identifier"),
                                  payload.get("reqId")))
        if state is None:
            return None
        if state.payload is None:
            state.payload = state.request.as_dict()
        return state if _strict_deep_eq(state.payload, payload) else None

    def votes(self, req_key: str) -> int:
        state = self.get(req_key)
        return len(state.propagates) if state else 0

    def is_finalised(self, req_key: str) -> bool:
        state = self.get(req_key)
        return state.finalised if state else False

    def set_finalised(self, req_key: str):
        if req_key in self:
            self[req_key].finalised = True

    def free(self, req_key: str):
        state = self.pop(req_key, None)
        if state is not None:
            ref = (state.request.identifier, state.request.reqId)
            if self._by_ref.get(ref) is state:
                del self._by_ref[ref]


class Propagator:
    # upper bound on entries per PROPAGATE_BATCH; the size budget below
    # is the real wire guard
    BATCH_LIMIT = 200
    # serialized-payload budget per batch: MSG_LEN_LIMIT (128 KiB) minus
    # generous envelope/AEAD headroom — chunking by count alone would
    # let large operations (multi-KB ATTRIB raws) build a frame the
    # stack drops wholesale, silently losing every propagate in it
    BATCH_SIZE_BUDGET = 128 * 1024 - 8 * 1024

    def __init__(self, name: str, quorums: Quorums, network,
                 forward_handler: Callable[[Request], None],
                 authenticator: Callable[[Request], bool] = None,
                 forward_batch_handler: Callable[[list], None] = None,
                 flat_wire_enabled: bool = False):
        """network: ExternalBus; forward_handler: called exactly once per
        finalised request (feeds ordering queues). authenticator(request)
        → bool gates requests FIRST LEARNED from a peer's PROPAGATE: a
        node must never echo-vote (or forward) content it cannot
        authenticate — otherwise a single byzantine relay plus the
        honest echo reaches the f+1 quorum with a forged payload (found
        by the TamperedPropagate adversary scenario). Requests from the
        client intake path were authenticated there already.
        forward_batch_handler(requests): optional columnar forward — all
        requests finalised by ONE inbound PROPAGATE_BATCH go to the
        ordering queues as one contiguous digest column (one downstream
        stash-replay per batch instead of per request)."""
        self.name = name
        self.quorums = quorums
        self._network = network
        self._forward = forward_handler
        self._forward_batch = forward_batch_handler
        self._authenticator = authenticator
        # flat zero-copy wire (common/serializers/flat_wire.py): each
        # queued payload is packed ONCE at queue time — the same bytes
        # feed the size budget AND the envelope, so the old pack-for-
        # sizing-then-discard cost disappears. Degrades to the typed
        # Propagate/PropagateBatch wire while an adversary tap is
        # installed (per-message granularity IS the fault-injection
        # seam) or when the flag is off.
        self._flat = flat_wire_enabled
        self.requests = Requests()
        self.metrics = NullMetricsCollector()   # node injects the real one
        self.tracer = NullTracer()              # node injects the real one
        # journey plane: node enables trace_context from config; stamps
        # flow only while the tracer is live, so the default NullTracer
        # keeps this seam free
        self.trace_context = False
        self._flush_seq = 0
        # queued outgoing propagates, flushed as PROPAGATE_BATCH once
        # per tick: at n validators every request is otherwise its own
        # message n-1 times per node — batching is what lets wide pools
        # (25 nodes) drain instead of drowning in per-message overhead
        self._out: list = []

    def update_quorums(self, quorums: Quorums):
        self.quorums = quorums

    def _next_stamp(self):
        """Advisory causal stamp for ONE outgoing envelope, or None
        when trace context is off. The clock pair is sampled HERE, at
        the flush seam — flat_wire's encode half is a PT012 consensus
        root and only ever sees the timestamps as plain arguments."""
        if not (self.trace_context and self.tracer.enabled):
            return None
        self._flush_seq += 1
        perf, wall = self.tracer.clock_pair()
        return flat_wire.TraceStamp(self.name, self._flush_seq,
                                    perf, wall)

    def _note_send(self, stamp, n: int, nbytes: int) -> None:
        """Send-side anchor for the journey joiner / Perfetto flow
        arrows: one instant per stamped envelope, keyed by flush seq."""
        if stamp is not None:
            self.tracer.instant("wire_send", CAT_PROPAGATE,
                                key=str(stamp.seq), seq=stamp.seq,
                                n=n, nbytes=nbytes)

    # ----------------------------------------------------------- sending

    def propagate(self, request: Request, client_name: Optional[str]):
        """Queue our PROPAGATE for this request (reference :204 sends
        immediately; here it rides the next flush's batch)."""
        state = self.requests.add(request)
        if self.name in state.propagates:
            return
        state.propagates.add(self.name)
        self._queue_out(request.as_dict(), client_name)
        self._try_finalise(request.key)

    def _queue_out(self, payload: dict, client_name) -> None:
        if self._flat:
            try:
                raw = _wire_serializer.serialize(payload)
                # estimate covers the client-id string + per-entry
                # offset-table overhead too; the post-encode split in
                # _send_flat_chunk backstops any remaining lag
                self._out.append((payload, client_name,
                                  len(raw) + len(client_name or "") + 24,
                                  raw))
                return
            except Exception:
                # unpackable oddity: ride the typed fallback below
                pass
        self._out.append((payload, client_name, _payload_size(payload),
                          None))

    def flush(self) -> int:
        """Send everything queued since the last flush, chunked under
        BOTH an entry-count cap and a serialized-size budget so no batch
        can exceed the transport frame limit. Called once per prod tick
        (and right after a client intake batch concludes). → messages
        queued count."""
        if not self._out:
            return 0
        with self.metrics.measure_time(MetricsName.PROPAGATE_FLUSH_TIME), \
                self.tracer.span("propagate_flush", CAT_PROPAGATE,
                                 n=len(self._out)):
            return self._flush()

    def _flush(self) -> int:
        out, self._out = self._out, []
        flat = self._flat and not getattr(self._network, "has_tap",
                                          False)

        def send_chunk(chunk):
            if flat and all(e[3] is not None for e in chunk):
                try:
                    self._send_flat_chunk(chunk)
                    return
                except flat_wire.FlatWireUnencodable as e:
                    # cannot ride the flat layout: typed fallback below
                    logger.debug("propagator: flat encode fell back "
                                 "(%s)", e)
            if len(chunk) == 1:
                # bare single-request sends carry no stamp — the
                # context is advisory and the batch forms carry it
                self._network.send(Propagate(request=chunk[0][0],
                                             senderClient=chunk[0][1]))
            else:
                stamp = self._next_stamp()
                self._network.send(PropagateBatch(
                    requests=[r for r, _, _, _ in chunk],
                    clients=[c or "" for _, c, _, _ in chunk],
                    traceCtx=stamp.as_list() if stamp else None))
                self._note_send(stamp, len(chunk), 0)

        chunk, chunk_size = [], 0
        for entry in out:
            size = entry[2]
            if chunk and (len(chunk) >= self.BATCH_LIMIT
                          or chunk_size + size > self.BATCH_SIZE_BUDGET):
                send_chunk(chunk)
                chunk, chunk_size = [], 0
            chunk.append(entry)
            chunk_size += size
        if chunk:
            send_chunk(chunk)
        return len(out)

    def _send_flat_chunk(self, chunk) -> None:
        """One flat envelope from the chunk's already-packed request
        blobs — the payload bytes computed for the size budget ARE the
        wire bytes; no second serialization happens."""
        stamp = self._next_stamp()
        trace = None
        if stamp is not None:
            trace = flat_wire.encode_trace_stamp(
                stamp.origin, stamp.seq, stamp.perf_ts, stamp.wall_ts)
        with self.tracer.span("wire_pack", CAT_PROPAGATE, n=len(chunk)):
            payload = flat_wire.encode_propagate_envelope(
                [raw for _, _, _, raw in chunk],
                [c or "" for _, c, _, _ in chunk],
                trace=trace)
        if len(payload) > self.BATCH_SIZE_BUDGET and len(chunk) > 1:
            # estimate lagged (same backstop as ThreePCOutbox): split
            # rather than build a frame the transport drops wholesale
            half = len(chunk) // 2
            self._send_flat_chunk(chunk[:half])
            self._send_flat_chunk(chunk[half:])
            return
        hub = get_seam_hub()
        hub.count(TM.WIRE_BYTES_SENT, len(payload))
        hub.observe(TM.WIRE_ENV_BYTES_PROPAGATE, len(payload))
        self._note_send(stamp, len(chunk), len(payload))
        self._network.send(FlatBatch(payload=payload))

    # ---------------------------------------------------------- receiving

    def process_propagate(self, msg: Propagate, frm: str):
        with self.metrics.measure_time(MetricsName.PROPAGATE_PROCESS_TIME):
            self._process_one(msg.request, msg.senderClient, frm)

    def process_propagate_batch(self, msg: PropagateBatch, frm: str):
        self.note_wire_stamp(getattr(msg, "traceCtx", None), frm)
        with self.metrics.measure_time(MetricsName.PROPAGATE_PROCESS_TIME):
            self._process_propagate_batch(msg, frm)

    def note_wire_stamp(self, ctx, frm: str) -> None:
        """Advisory typed-fallback stamp intake: decode the nullable
        traceCtx field and record a receive-side anchor instant. Every
        failure mode is swallowed into 'no journey hop' — the stamp can
        never affect propagate handling (plenum-lint PT015 pins this
        unreachability from consensus)."""
        if ctx is None or not self.tracer.enabled:
            return
        stamp = flat_wire.TraceStamp.from_wire(ctx)
        if stamp is None:
            return
        recv_perf, recv_wall = self.tracer.clock_pair()
        self.tracer.instant(
            "wire_recv", CAT_PROPAGATE,
            key="%s:%d" % (stamp.origin, stamp.seq),
            origin=stamp.origin, seq=stamp.seq, frm=frm,
            sent_perf=stamp.perf_ts, sent_wall=stamp.wall_ts,
            recv_wall=recv_wall)

    def _process_propagate_batch(self, msg: PropagateBatch, frm: str):
        clients = msg.clients or [""] * len(msg.requests)
        if len(clients) != len(msg.requests):
            # malformed (byzantine?) batch: dropping it silently via zip
            # truncation would make a protocol violation invisible
            logger.warning(
                "%s: PROPAGATE_BATCH from %s with %d requests but %d "
                "clients — discarded", self.name, frm,
                len(msg.requests), len(clients))
            return
        if self._forward_batch is None:
            for payload, client in zip(msg.requests, clients):
                self._process_one(payload, client or None, frm)
            return
        # columnar finalisation: requests that reach quorum inside this
        # batch collect into one forward call — their digests stay a
        # contiguous column all the way into the ordering queues
        finalised: list = []
        for payload, client in zip(msg.requests, clients):
            self._process_one(payload, client or None, frm,
                              finalise_sink=finalised)
        if finalised:
            self._forward_batch([s.request for s in finalised])

    def process_propagate_columns(self, cols, frm: str):
        """Flat-wire PROPAGATE intake: the parsed section hands each
        request payload over as raw msgpack bytes, unpacked straight
        into the dict ``_process_one`` needs — no Propagate message
        object, no envelope schema validation, no per-field canonical
        re-sort on the receive path. Finalisation stays columnar: all
        requests reaching quorum inside this envelope forward as one
        contiguous digest column."""
        with self.metrics.measure_time(MetricsName.PROPAGATE_PROCESS_TIME):
            self._process_propagate_columns(cols, frm)

    def _process_propagate_columns(self, cols, frm: str):
        sink = [] if self._forward_batch is not None else None
        for i in range(cols.n):
            try:
                payload = cols.request(i)
            except Exception:
                # one bad entry costs ONE propagate, never the envelope
                logger.warning(
                    "%s: bad PROPAGATE entry in flat envelope from %s "
                    "— ignored", self.name, frm)
                continue
            self._process_one(payload, cols.client(i) or None, frm,
                              finalise_sink=sink)
        if sink:
            self._forward_batch([s.request for s in sink])

    def _process_one(self, payload: dict, sender_client, frm: str,
                     finalise_sink=None):
        # ONE state lookup per propagate: at n validators this handler
        # runs (n-1) times per request per node — every extra dict hop
        # or digest-property access in here is multiplied by that
        quick = self.requests.ref_state(payload)
        if quick is not None and quick.forwarded:
            # already queued for ordering: no propagate — matching OR
            # byzantine-variant — can change anything, so skip the
            # deep-equality check entirely. At 25 nodes most of the
            # (n-1) gossip copies of every request land here.
            return
        state = self.requests.lookup_state(payload)
        if state is None:
            # first sighting of this exact content — it must
            # authenticate before it may collect votes or be echoed
            try:
                request = Request.from_dict(payload)
            except Exception:
                logger.warning("%s: malformed PROPAGATE payload from %s "
                               "— ignored", self.name, frm)
                return
            if self._authenticator is not None \
                    and not self._authenticator(request):
                logger.warning(
                    "%s: PROPAGATE from %s fails authentication "
                    "(identifier=%s reqId=%s) — ignored, not echoed",
                    self.name, frm, payload.get("identifier"),
                    payload.get("reqId"))
                return
            state = self.requests.add(request)
        propagates = state.propagates
        n0 = len(propagates)
        propagates.add(frm)
        # echo our own propagate if we haven't yet (so slow clients still
        # reach quorum via node-to-node gossip)
        if self.name not in propagates:
            propagates.add(self.name)
            self._queue_out(payload, sender_client)
        if not state.forwarded and \
                self.quorums.propagate.is_reached(len(propagates)):
            closer = frm
            if self.tracer.enabled and len(propagates) > n0 + 1 \
                    and not self.quorums.propagate.is_reached(n0 + 1):
                # both the relay's vote and our own echo landed in this
                # call and the relay's alone did not reach f+1: our own
                # echo supplied the closing vote
                closer = self.name
            self._finalise(state, finalise_sink, closer=closer)

    def _try_finalise(self, req_key: str):
        state = self.requests.get(req_key)
        if state is None or state.forwarded:
            return
        if self.quorums.propagate.is_reached(len(state.propagates)):
            self._finalise(state, closer=self.name)

    def _finalise(self, state: ReqState, sink=None, closer=None):
        """Quorum reached: mark, record the lifecycle marker (naming
        the relay whose vote supplied the f+1'th — the journey plane's
        propagate-close attribution), forward exactly once. The digest
        access is free here — forwarding hands request.key to the
        ordering queues anyway. With a `sink` the caller owns
        forwarding (batch path: one columnar forward per inbound
        PROPAGATE_BATCH)."""
        state.finalised = True
        state.forwarded = True
        self.tracer.instant("propagate_quorum", CAT_PROPAGATE,
                            key=state.request.key,
                            votes=len(state.propagates),
                            closer=closer or self.name)
        if sink is not None:
            sink.append(state)
        else:
            self._forward(state.request)
