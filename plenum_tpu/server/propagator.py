"""Propagator — client-request propagation and finalization.

Reference: plenum/server/propagator.py — `Requests` (:62, digest →
request + votes), `Propagator` (:195): on a new client request, broadcast
PROPAGATE; once f+1 nodes propagated identical requests the request is
"finalised" and forwarded to the ordering queues.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set

from plenum_tpu.common.messages.node_messages import Propagate
from plenum_tpu.common.request import Request
from plenum_tpu.consensus.quorums import Quorums

logger = logging.getLogger(__name__)


def _strict_deep_eq_py(a, b) -> bool:
    """Deep equality that also requires identical types at every node —
    digest-faithful for the canonical serializers (which encode True,
    1, and 1.0 differently while Python `==` conflates them)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):
        if len(a) != len(b):
            return False
        for k, v in a.items():
            if k not in b or not _strict_deep_eq_py(v, b[k]):
                return False
        return True
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(
            _strict_deep_eq_py(x, y) for x, y in zip(a, b))
    return a == b


from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")
if _fp is not None:
    def _strict_deep_eq(a, b, _c=_fp.deep_eq):
        try:
            return _c(a, b)
        except TypeError:  # structure too deep for the C guard
            return _strict_deep_eq_py(a, b)
else:
    _strict_deep_eq = _strict_deep_eq_py


class ReqState:
    def __init__(self, request: Request):
        self.request = request
        self.propagates: Set[str] = set()
        self.finalised = False
        self.forwarded = False
        self.executed = False
        self.payload = None      # canonical as_dict(), built on first use


class Requests(dict):
    """digest → ReqState (reference propagator.py:62).

    A (identifier, reqId) side-index lets the propagate path recognise a
    request it already holds WITHOUT recomputing the digest — computing
    the key costs a canonical serialization + sha256, and with n nodes
    gossiping every request arrives n-1 times (the dominant per-request
    cost at 25 nodes). On an index hit the incoming payload is compared
    to the stored request's dict (plain dict equality, no hashing); a
    mismatch (byzantine reuse of a reqId with different content) falls
    back to the full digest path."""

    def __init__(self):
        super().__init__()
        self._by_ref: dict = {}          # (identifier, reqId) → digest

    def add(self, req: Request) -> ReqState:
        if req.key not in self:
            self[req.key] = ReqState(req)
            self._by_ref[(req.identifier, req.reqId)] = req.key
        return self[req.key]

    def add_propagate(self, req: Request, sender: str):
        state = self.add(req)
        state.propagates.add(sender)

    def lookup_payload(self, payload: dict) -> Optional[Request]:
        """Cheap pre-digest lookup: the stored Request if `payload` is
        bit-for-bit the request we already hold, else None. Equality is
        TYPE-STRICT deep comparison — the digest's canonical
        serialization distinguishes True/1/1.0, so plain dict equality
        (which conflates them) would let a byzantine re-gossip count as
        a vote for the original digest; any mismatch falls back to the
        full digest path."""
        digest = self._by_ref.get((payload.get("identifier"),
                                   payload.get("reqId")))
        if digest is None:
            return None
        state = self.get(digest)
        if state is None:
            return None
        if state.payload is None:
            state.payload = state.request.as_dict()
        if _strict_deep_eq(state.payload, payload):
            return state.request
        return None

    def votes(self, req_key: str) -> int:
        state = self.get(req_key)
        return len(state.propagates) if state else 0

    def is_finalised(self, req_key: str) -> bool:
        state = self.get(req_key)
        return state.finalised if state else False

    def set_finalised(self, req_key: str):
        if req_key in self:
            self[req_key].finalised = True

    def free(self, req_key: str):
        state = self.pop(req_key, None)
        if state is not None:
            ref = (state.request.identifier, state.request.reqId)
            if self._by_ref.get(ref) == req_key:
                del self._by_ref[ref]


class Propagator:
    def __init__(self, name: str, quorums: Quorums, network,
                 forward_handler: Callable[[Request], None]):
        """network: ExternalBus; forward_handler: called exactly once per
        finalised request (feeds ordering queues)."""
        self.name = name
        self.quorums = quorums
        self._network = network
        self._forward = forward_handler
        self.requests = Requests()

    def update_quorums(self, quorums: Quorums):
        self.quorums = quorums

    # ----------------------------------------------------------- sending

    def propagate(self, request: Request, client_name: Optional[str]):
        """Broadcast our PROPAGATE for this request (reference :204)."""
        state = self.requests.add(request)
        if self.name in state.propagates:
            return
        state.propagates.add(self.name)
        self._network.send(Propagate(request=request.as_dict(),
                                     senderClient=client_name))
        self._try_finalise(request.key)

    # ---------------------------------------------------------- receiving

    def process_propagate(self, msg: Propagate, frm: str):
        request = self.requests.lookup_payload(msg.request)
        if request is None:
            request = Request.from_dict(msg.request)
        self.requests.add_propagate(request, frm)
        # echo our own propagate if we haven't yet (so slow clients still
        # reach quorum via node-to-node gossip)
        state = self.requests[request.key]
        if self.name not in state.propagates:
            state.propagates.add(self.name)
            self._network.send(Propagate(request=msg.request,
                                         senderClient=msg.senderClient))
        self._try_finalise(request.key)

    def _try_finalise(self, req_key: str):
        state = self.requests.get(req_key)
        if state is None or state.forwarded:
            return
        if self.quorums.propagate.is_reached(len(state.propagates)):
            state.finalised = True
            state.forwarded = True
            self._forward(state.request)
