"""Batch handlers — per-ledger batch lifecycle, chained per ledger.

Reference: plenum/server/batch_handlers/ — `BatchRequestHandler` ABC with
post_batch_applied / commit_batch / post_batch_rejected, and the concrete
chain: AuditBatchHandler (audit_batch_handler.py:20, _create_audit_txn_data
:83 — the recovery backbone: one audit txn per ordered batch recording all
ledger/state roots, view_no, primaries, node_reg), Domain/Pool/Config
handlers (ledger+state staging), TsStoreBatchHandler (timestamp → state
root index), PrimaryBatchHandler / NodeRegHandler (node registry
snapshots inside the audit data).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, AUDIT_TXN, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID,
    POOL_LEDGER_ID)
from plenum_tpu.common.txn_util import get_payload_data, init_empty_txn
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.three_pc_batch import ThreePcBatch

# audit txn payload fields (reference plenum/common/constants.py AUDIT_TXN_*)
AUDIT_TXN_VIEW_NO = "viewNo"
AUDIT_TXN_PP_SEQ_NO = "ppSeqNo"
AUDIT_TXN_LEDGERS_SIZE = "ledgerSize"
AUDIT_TXN_LEDGER_ROOT = "ledgerRoot"
AUDIT_TXN_STATE_ROOT = "stateRoot"
AUDIT_TXN_PRIMARIES = "primaries"
AUDIT_TXN_NODE_REG = "nodeReg"
AUDIT_TXN_DIGEST = "digest"


class BatchRequestHandler(ABC):
    def __init__(self, database_manager: DatabaseManager, ledger_id: int):
        self.database_manager = database_manager
        self.ledger_id = ledger_id

    @property
    def ledger(self):
        return self.database_manager.get_ledger(self.ledger_id)

    @property
    def state(self):
        return self.database_manager.get_state(self.ledger_id)

    @abstractmethod
    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None): ...

    @abstractmethod
    def post_batch_rejected(self, ledger_id: int, prev_result=None): ...

    @abstractmethod
    def commit_batch(self, batch: ThreePcBatch, prev_result=None): ...


class LedgerBatchHandler(BatchRequestHandler):
    """Generic ledger+state staging for a writable ledger (the common
    behavior of Domain/Pool/ConfigBatchHandler in the reference)."""

    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None):
        # txns were staged by WriteRequestManager.apply_request; nothing
        # further until commit
        return None

    def post_batch_rejected(self, ledger_id: int, prev_result=None):
        # reverts are driven centrally by WriteRequestManager, which
        # knows each staged batch's ledger and size
        return None

    def commit_batch(self, batch: ThreePcBatch, prev_result=None):
        count = len(batch.valid_digests)
        _, committed = self.ledger.commitTxns(count)
        if self.state is not None:
            from plenum_tpu.utils.metrics import MetricsName
            root = (self.ledger.strToHash(batch.state_root)
                    if batch.state_root else None)
            with self.database_manager.metrics.measure_time(
                    MetricsName.STATE_COMMIT_TIME):
                self.state.commit(rootHash=root)
        return committed


class DomainBatchHandler(LedgerBatchHandler):
    def __init__(self, dm):
        super().__init__(dm, DOMAIN_LEDGER_ID)


class PoolBatchHandler(LedgerBatchHandler):
    def __init__(self, dm):
        super().__init__(dm, POOL_LEDGER_ID)


class ConfigBatchHandler(LedgerBatchHandler):
    def __init__(self, dm):
        super().__init__(dm, CONFIG_LEDGER_ID)


class TsStoreBatchHandler(BatchRequestHandler):
    """Records (pp_time → committed state root) per batch so reads can
    resolve state-at-a-timestamp (reference
    plenum/server/batch_handlers/ts_store_batch_handler.py). Registered
    on the AUDIT chain, which runs for every ordered batch regardless of
    its target ledger."""

    def __init__(self, dm):
        super().__init__(dm, AUDIT_LEDGER_ID)

    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None):
        return None

    def post_batch_rejected(self, ledger_id: int, prev_result=None):
        return None

    def commit_batch(self, batch: ThreePcBatch, prev_result=None):
        store = self.database_manager.get_store("state_ts")
        state = self.database_manager.get_state(batch.ledger_id)
        if store is None or state is None:
            return None
        store.set(batch.pp_time, state.committedHeadHash, batch.ledger_id)
        return None


class AuditBatchHandler(BatchRequestHandler):
    """One audit txn per ordered batch — the recovery backbone
    (reference audit_batch_handler.py:20, docs/source/audit_ledger.md)."""

    def __init__(self, dm: DatabaseManager):
        super().__init__(dm, AUDIT_LEDGER_ID)

    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None):
        txn = self._create_audit_txn(batch)
        self.ledger.append_txns_metadata([txn], batch.pp_time)
        self.ledger.appendTxns([txn])
        return txn

    def post_batch_rejected(self, ledger_id: int, prev_result=None):
        # reverts are driven centrally by WriteRequestManager
        return None

    def commit_batch(self, batch: ThreePcBatch, prev_result=None):
        _, committed = self.ledger.commitTxns(1)
        return committed[0] if committed else None

    def _create_audit_txn(self, batch: ThreePcBatch) -> dict:
        """reference audit_batch_handler.py:83 _create_audit_txn_data.

        Every field must depend only on batch-original data (original
        view, primaries of the ORIGINAL view, pp digest, roots) so that
        re-applying the same old-view PrePrepare after a view change
        yields a bit-identical audit txn — the re-apply root comparison
        in the ordering service depends on it."""
        txn = init_empty_txn(AUDIT_TXN)
        data = get_payload_data(txn)
        data[AUDIT_TXN_VIEW_NO] = batch.original_view_no
        data[AUDIT_TXN_PP_SEQ_NO] = batch.pp_seq_no
        data[AUDIT_TXN_DIGEST] = batch.pp_digest
        sizes, ledger_roots, state_roots = {}, {}, {}
        for lid in sorted(self.database_manager.ledger_ids):
            if lid == AUDIT_LEDGER_ID:
                continue
            ledger = self.database_manager.get_ledger(lid)
            state = self.database_manager.get_state(lid)
            sizes[str(lid)] = ledger.uncommitted_size
            ledger_roots[str(lid)] = ledger.hashToStr(
                ledger.uncommitted_root_hash)
            if state is not None:
                state_roots[str(lid)] = ledger.hashToStr(state.headHash)
        data[AUDIT_TXN_LEDGERS_SIZE] = sizes
        data[AUDIT_TXN_LEDGER_ROOT] = ledger_roots
        data[AUDIT_TXN_STATE_ROOT] = state_roots
        data[AUDIT_TXN_PRIMARIES] = self._fill_primaries(batch)
        if batch.node_reg is not None:
            data[AUDIT_TXN_NODE_REG] = batch.node_reg
        return txn

    def _fill_primaries(self, batch: ThreePcBatch):
        """Delta-encode primaries (reference _fill_primaries): store the
        list only when it changed; otherwise an int = how many audit txns
        back the last stored list is. Keeps every steady-state audit txn
        identical in shape AND lets recovery resolve primaries at any
        seq_no."""
        last_seq = self.ledger.uncommitted_size
        last_txn = self.ledger.get_by_seq_no_uncommitted(last_seq) \
            if last_seq else None
        if last_txn is None:
            return batch.primaries
        last_value = get_payload_data(last_txn).get(AUDIT_TXN_PRIMARIES)
        if isinstance(last_value, int):
            anchor_seq = last_seq - last_value
            anchor = self.ledger.get_by_seq_no_uncommitted(anchor_seq)
            anchor_primaries = get_payload_data(anchor).get(
                AUDIT_TXN_PRIMARIES) if anchor else None
            if anchor_primaries == batch.primaries:
                return last_value + 1
            return batch.primaries
        if last_value == batch.primaries:
            return 1
        return batch.primaries

    def primaries_at(self, seq_no: int):
        """Resolve the primaries list effective at audit seq_no (follows
        the delta chain) — recovery/catchup helper."""
        txn = self.ledger.get_by_seq_no_uncommitted(seq_no)
        if txn is None:
            return None
        value = get_payload_data(txn).get(AUDIT_TXN_PRIMARIES)
        if isinstance(value, int):
            anchor = self.ledger.get_by_seq_no_uncommitted(seq_no - value)
            return get_payload_data(anchor).get(AUDIT_TXN_PRIMARIES) \
                if anchor else None
        return value

    def audit_root_for_pre_prepare(self) -> str:
        return self.ledger.hashToStr(self.ledger.uncommitted_root_hash)
