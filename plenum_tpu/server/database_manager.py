"""DatabaseManager — ledger_id → (ledger, state) registry + named stores.

Reference: plenum/server/database_manager.py:11 (register_new_database :23).
"""
from typing import Dict, Optional

from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.state.pruning_state import State


class Database:
    def __init__(self, ledger: Ledger, state: Optional[State],
                 taa_acceptance_required: bool = True):
        self.ledger = ledger
        self.state = state
        self.taa_acceptance_required = taa_acceptance_required


class DatabaseManager:
    def __init__(self):
        from plenum_tpu.utils.metrics import NullMetricsCollector
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.databases: Dict[int, Database] = {}
        self.stores: Dict[str, object] = {}
        self._init_hooks = []
        # state_root → MultiSignature store, set by the Node when BLS is
        # enabled; read handlers attach it to state proofs (reference
        # plenum/server/database_manager.py:112 bls_store property)
        self.bls_store = None
        # lid → committed state root pinned for read serving while the
        # node recovers (catchup / view change): roots committed txn-by-
        # txn during catchup carry no BLS multi-sig yet, so serving them
        # would strip the proof's multi_signature mid-recovery. The MPT
        # keeps history, so the pinned (pre-recovery, BLS-signed) root
        # stays readable and provable until the node unpins.
        self._pinned_read_roots: Dict[int, bytes] = {}

    def pin_read_roots(self):
        """Pin every state's current committed root: proof-bearing reads
        keep answering from it until unpin_read_roots (graceful read
        degradation during view change / catchup). Already-pinned
        ledgers are left alone — a view change starting MID-catchup
        must not overwrite the pre-recovery signed pin with an unsigned
        intermediate root catchup just committed."""
        for lid, db in self.databases.items():
            if db.state is not None and lid not in self._pinned_read_roots:
                root = db.state.committedHeadHash
                if root is not None:
                    self._pinned_read_roots[lid] = bytes(root)

    def unpin_read_roots(self):
        self._pinned_read_roots.clear()

    def pinned_read_root(self, lid) -> Optional[bytes]:
        return self._pinned_read_roots.get(lid)

    @property
    def reads_degraded(self) -> bool:
        """True while reads serve pinned (pre-recovery) roots."""
        return bool(self._pinned_read_roots)

    def register_new_database(self, lid: int, ledger: Ledger,
                              state: Optional[State] = None,
                              taa_acceptance_required: bool = True):
        if lid in self.databases:
            raise ValueError("ledger {} already registered".format(lid))
        self.databases[lid] = Database(ledger, state,
                                       taa_acceptance_required)

    def get_database(self, lid) -> Optional[Database]:
        return self.databases.get(lid)

    def get_ledger(self, lid) -> Optional[Ledger]:
        db = self.databases.get(lid)
        return db.ledger if db else None

    def get_state(self, lid) -> Optional[State]:
        db = self.databases.get(lid)
        return db.state if db else None

    def register_new_store(self, label: str, store):
        self.stores[label] = store

    def get_store(self, label: str):
        return self.stores.get(label)

    @property
    def ledger_ids(self):
        return list(self.databases.keys())

    def is_taa_acceptance_required(self, lid: int) -> bool:
        db = self.databases.get(lid)
        return db.taa_acceptance_required if db else False
