"""Ledger freezing: retire plugin ledgers while preserving their final
roots for audit. Frozen ledgers accept no writes (enforced in
WriteRequestManager.dynamic_validation); the leecher never syncs
plugin ledgers, so no catchup exclusion is needed here.

Reference: plenum/server/request_handlers/ledgers_freeze/ —
LedgersFreezeHandler (TRUSTEE-only write on the config ledger recording
{ledger_id: {ledger, state, seq_no}} final roots from the audit
ledger), GetFrozenLedgersHandler (read), StaticLedgersFreezeHelper
(state path "4:FROZEN_LEDGERS" — same marker here for state-proof
compatibility).
"""
from __future__ import annotations

from typing import Dict, Optional

from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID,
    GET_FROZEN_LEDGERS, LEDGERS_FREEZE, ROLE, TRUSTEE, VALID_LEDGER_IDS)
from plenum_tpu.common.exceptions import (
    InvalidClientRequest, UnauthorizedClientRequest)
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import (
    get_payload_data, get_seq_no, get_txn_time)
from plenum_tpu.server.batch_handlers import (
    AUDIT_TXN_LEDGER_ROOT, AUDIT_TXN_LEDGERS_SIZE, AUDIT_TXN_STATE_ROOT)
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.execution_lanes import TouchedKeys
from plenum_tpu.server.request_handlers import (
    ReadRequestHandler, WriteRequestHandler, decode_state_value,
    encode_state_value, nym_to_state_key)

LEDGERS_IDS = "ledgers_ids"
FROZEN_LEDGERS_PATH = b"4:FROZEN_LEDGERS"


def get_frozen_ledgers(config_state, is_committed: bool = True
                       ) -> Dict[int, dict]:
    if config_state is None:
        return {}
    raw = config_state.get(FROZEN_LEDGERS_PATH, isCommitted=is_committed)
    val, _, _ = decode_state_value(raw)
    return {int(k): v for k, v in (val or {}).items()}


class LedgersFreezeHandler(WriteRequestHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, LEDGERS_FREEZE,
                         CONFIG_LEDGER_ID)

    def static_validation(self, request: Request):
        lids = request.operation.get(LEDGERS_IDS)
        if not isinstance(lids, list) or not lids or \
                not all(isinstance(lid, int) for lid in lids):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "ledgers_ids must be a non-empty list of ints")
        if any(lid in VALID_LEDGER_IDS for lid in lids):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "base ledgers {} can't be frozen".format(
                    tuple(VALID_LEDGER_IDS)))

    def touched_keys(self, request: Request):
        """One fixed config key (the frozen-ledger registry) plus the
        author's domain record — both computable from the request, so
        freezes lane-plan despite reading the audit ledger (lane keys
        cover STATE touches; ledger reads don't conflict)."""
        return TouchedKeys(
            reads=((CONFIG_LEDGER_ID, FROZEN_LEDGERS_PATH),
                   (DOMAIN_LEDGER_ID,
                    nym_to_state_key(request.identifier or ""))),
            writes=((CONFIG_LEDGER_ID, FROZEN_LEDGERS_PATH),))

    def dynamic_validation(self, request: Request, req_pp_time=None):
        domain_state = self.database_manager.get_state(DOMAIN_LEDGER_ID)
        val, _, _ = decode_state_value(domain_state.get(
            nym_to_state_key(request.identifier or ""), isCommitted=False))
        if (val or {}).get(ROLE) != TRUSTEE:
            raise UnauthorizedClientRequest(
                request.identifier, request.reqId,
                "only TRUSTEE can freeze ledgers")
        audit = self.database_manager.get_ledger(AUDIT_LEDGER_ID)
        if audit is None or audit.size == 0:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "no audit history to freeze ledgers against")
        sizes = get_payload_data(audit.get_last_txn()).get(
            AUDIT_TXN_LEDGERS_SIZE) or {}
        missing = [lid for lid in request.operation[LEDGERS_IDS]
                   if str(lid) not in sizes]
        if missing:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "ledgers {} have never existed".format(missing))

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        seq_no, txn_time = get_seq_no(txn), get_txn_time(txn)
        lids = get_payload_data(txn)[LEDGERS_IDS]
        frozen = {str(k): v for k, v in get_frozen_ledgers(
            self.state, is_committed=False).items()}
        audit_data = get_payload_data(
            self.database_manager.get_ledger(AUDIT_LEDGER_ID)
            .get_last_txn())
        for lid in lids:
            frozen[str(lid)] = {
                "ledger": (audit_data.get(AUDIT_TXN_LEDGER_ROOT)
                           or {}).get(str(lid)),
                "state": (audit_data.get(AUDIT_TXN_STATE_ROOT)
                          or {}).get(str(lid)),
                "seq_no": (audit_data.get(AUDIT_TXN_LEDGERS_SIZE)
                           or {}).get(str(lid), 0),
            }
        self.state.set(FROZEN_LEDGERS_PATH,
                       encode_state_value(frozen, seq_no, txn_time))
        return frozen


class GetFrozenLedgersHandler(ReadRequestHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, GET_FROZEN_LEDGERS,
                         CONFIG_LEDGER_ID)

    def get_result(self, request: Request) -> dict:
        frozen = get_frozen_ledgers(self.state, is_committed=True)
        return {"identifier": request.identifier, "reqId": request.reqId,
                "type": GET_FROZEN_LEDGERS,
                "data": {str(k): v for k, v in frozen.items()} or None}
