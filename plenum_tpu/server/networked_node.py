"""NetworkedNode — a consensus Node on real sockets.

Reference: plenum/server/node.py owns NodeZStack + ClientZStack and its
`prod` (node.py:1037) services stacks, replicas, timer, and flushes
outboxes every tick (§3.2). Here the same wiring is a thin Prodable
around the rung-2-tested Node core: inbound wire dicts are deserialized
through the message factory and fed to the node's ExternalBus; the
node's sends are serialized onto the NodeStack's per-remote outboxes and
flushed once per tick; client frames go to process_client_request and
replies back through the ClientStack.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from plenum_tpu.common.config import Config
from plenum_tpu.common.messages.message_factory import node_message_factory
from plenum_tpu.runtime.bus import ExternalBus
from plenum_tpu.runtime.motor import Prodable
from plenum_tpu.runtime.timer import QueueTimer
from plenum_tpu.network.keys import NodeKeys
from plenum_tpu.network.stack import (
    HA, ClientStack, NodeStack, RemoteInfo)
from plenum_tpu.server.node import Node
from plenum_tpu.utils.metrics import MetricsName

logger = logging.getLogger(__name__)


class NetworkedNode(Prodable):
    def __init__(self, name: str, registry: Dict[str, RemoteInfo],
                 keys: NodeKeys, node_ha: HA, client_ha: HA,
                 config: Optional[Config] = None,
                 timer: Optional[QueueTimer] = None,
                 storage_factory=None,
                 genesis_txns: Optional[List[dict]] = None,
                 metrics=None, info_dir: Optional[str] = None):
        import time
        self._name = name
        self.config = config or Config()
        # wall-clock timer: ppTime/TimestampField expect epoch seconds
        self.timer = timer or QueueTimer(get_current_time=time.time)
        self.registry = dict(registry)

        self.nodestack = NodeStack(
            name, node_ha, keys, registry, self.config,
            on_connections_changed=self._on_conns_changed)
        self.clientstack = ClientStack(name + ".client", client_ha, keys,
                                       self.config)

        # the ExternalBus the consensus core sees; its send handler feeds
        # the stack outboxes
        self.bus = ExternalBus(send_handler=self._send_to_nodes)
        validators = sorted(registry)
        # BLS signer derived from the same seed the transport identity
        # uses — deterministic, so it matches the blskey the bootstrap
        # scripts put in the genesis NODE txn (bootstrap.py:58)
        bls_signer = None
        if getattr(self.config, "BLS_SIGN", True):
            from plenum_tpu.crypto.bls import BlsCryptoSignerPlenum
            bls_signer, _ = BlsCryptoSignerPlenum.generate(keys.seed)
        self.node = Node(name, validators, self.timer, self.bus,
                         config=self.config,
                         storage_factory=storage_factory,
                         client_reply_handler=self._reply_to_client,
                         genesis_txns=genesis_txns,
                         bls_signer=bls_signer,
                         metrics=metrics)

        # periodic metrics flush + validator-info dump (reference
        # node.py: dump_additional_info / flush on prod)
        from plenum_tpu.runtime.timer import RepeatingTimer

        def _guarded(label, fn):
            # a transient I/O error must neither crash the prod tick nor
            # kill the repeating timer
            def run():
                try:
                    fn()
                except Exception:
                    logger.warning("%s: %s failed", name, label,
                                   exc_info=True)
            return run

        if metrics is not None:
            RepeatingTimer(self.timer, self.config.METRICS_FLUSH_INTERVAL,
                           _guarded("metrics flush",
                                    metrics.flush_accumulated))
        self.info_tool = None
        if info_dir is not None:
            from plenum_tpu.server.validator_info import (
                ValidatorNodeInfoTool)
            self.info_tool = ValidatorNodeInfoTool(self.node,
                                                   metrics=metrics)
            RepeatingTimer(
                self.timer, self.config.VALIDATOR_INFO_DUMP_INTERVAL,
                _guarded("validator-info dump",
                         lambda: self.info_tool.dump_json_file(info_dir)))

    # --------------------------------------------------------- tx glue

    def _send_to_nodes(self, message, dst=None):
        self.nodestack.send(message.to_dict(), dst)

    def _reply_to_client(self, client_id: str, msg):
        # queued: a committed batch's replies coalesce into per-client
        # BATCH frames at the end-of-tick flush
        self.clientstack.queue_to_client(client_id, msg.to_dict())

    def _on_conns_changed(self, connecteds):
        self.bus.update_connecteds(set(connecteds))

    # --------------------------------------------------------- rx glue

    def _on_node_wire_msg(self, msg_dict: dict, frm: str):
        try:
            msg = node_message_factory.get_instance(**msg_dict)
        except Exception as e:
            logger.warning("%s: invalid message from %s: %s",
                           self._name, frm, e)
            return
        self.bus.process_incoming(msg, frm)

    def _on_client_wire_msg(self, msg_dict: dict, client_id: str):
        self.node.process_client_request(msg_dict, client_id)

    # Batched client intake with deferred harvest: each tick's client
    # frames become ONE verifier dispatch (device batch / daemon frame);
    # the result is harvested on a later tick once it has landed, so the
    # verification round trip overlaps consensus work instead of
    # blocking the prod loop (same pipelining the in-process bench pool
    # gets from dispatch/conclude). While a batch is in flight, newly
    # arrived frames BUFFER (never a blocking conclude inside prod —
    # that would stall every consensus tick for a device round trip);
    # the buffered frames become the next, deeper dispatch.
    _pending_auth = None
    _pending_since = None
    _client_buf: list

    def _collect_client_msgs(self) -> int:
        import time as _time
        buf = self.__dict__.setdefault("_client_buf", [])
        count = self.clientstack.service(
            lambda d, cid: buf.append((d, cid)),
            quota=self.config.CLIENT_TO_NODE_STACK_QUOTA,
            size_quota=self.config.CLIENT_TO_NODE_STACK_SIZE)
        if self._pending_auth is not None:
            # liveness fallback: a wedged daemon/device must not buffer
            # forever — after the timeout, harvest blocking
            if _time.monotonic() - self._pending_since > \
                    self.config.CLIENT_AUTH_TIMEOUT:
                pending, self._pending_auth = self._pending_auth, None
                logger.warning("%s: verify batch fallback harvest after "
                            "%.1fs", self._name,
                            _time.monotonic() - self._pending_since)
                self.node.conclude_client_batch(pending)
            else:
                return count
        if buf:
            self._client_buf = []
            self._pending_auth = self.node.dispatch_client_batch(buf)
            self._pending_since = _time.monotonic()
            logger.debug("%s: dispatched verify batch of %d",
                        self._name, len(buf))
            # a coalescing provider (tpu_hub) needs an explicit flush to
            # start its launch — in this process nothing else will
            self.node.authnr.flush()
        return count

    # -------------------------------------------------------- Prodable

    @property
    def name(self) -> str:
        return self._name

    def start(self, loop) -> None:
        loop.create_task(self.nodestack.start())
        loop.create_task(self.clientstack.start())

    async def start_async(self):
        await self.nodestack.start()
        await self.clientstack.start()

    def stop(self) -> None:
        import asyncio
        for stack in (self.nodestack, self.clientstack):
            try:
                asyncio.get_event_loop().create_task(stack.stop())
            except RuntimeError:
                pass

    async def prod(self, limit: int = None) -> int:
        """One tick (reference node.py:1037): rx quotas → consensus →
        timer → lifecycle → flush."""
        # harvest a landed verification batch before taking new work
        if self._pending_auth is not None and \
                self.node.client_batch_ready(self._pending_auth):
            import time as _time
            pending, self._pending_auth = self._pending_auth, None
            logger.debug("%s: verify batch landed after %.2fs", self._name,
                        _time.monotonic() - (self._pending_since or 0))
            self.node.conclude_client_batch(pending)
        metrics = self.node.metrics
        if self.nodestack.metrics is not metrics:
            self.nodestack.metrics = metrics
            self.clientstack.metrics = metrics
        with metrics.measure_time(MetricsName.NODE_RX_TIME):
            c = self.nodestack.service(
                self._on_node_wire_msg,
                quota=self.config.NODE_TO_NODE_STACK_QUOTA,
                size_quota=self.config.NODE_TO_NODE_STACK_SIZE)
        with metrics.measure_time(MetricsName.CLIENT_RX_TIME):
            c += self._collect_client_msgs()
        c += self.node.service()
        with metrics.measure_time(MetricsName.TIMER_SERVICE_TIME):
            c += self.timer.service()
        with metrics.measure_time(MetricsName.LIFECYCLE_TIME):
            self.nodestack.service_lifecycle()
        with metrics.measure_time(MetricsName.TRANSPORT_FLUSH_TIME):
            flushed = self.nodestack.flush_outboxes()
            self.clientstack.flush_client_outboxes()
        if flushed:
            metrics.add_event(MetricsName.TRANSPORT_BATCH_SIZE, flushed)
        return c
