"""Request handlers — per-txn-type validation/apply logic.

Reference: plenum/server/request_handlers/ — `WriteRequestHandler`,
`ReadRequestHandler` interfaces (handler_interfaces/*.py), concrete NYM
(nym_handler.py), NODE (node_handler.py), GET_TXN (get_txn_handler.py),
audit (audit_handler.py — its batch-level logic lives in
batch_handlers.py here).

A write handler implements:
  static_validation(request)    — schema-level, no state
  dynamic_validation(request)   — against uncommitted state
  update_state(txn, prev, req)  — apply to the head (uncommitted) state
"""
from __future__ import annotations

import json
from abc import ABC, abstractmethod
from typing import Optional

from plenum_tpu.common.constants import (
    DATA, DOMAIN_LEDGER_ID, GET_TXN, NODE, NYM, POOL_LEDGER_ID, ROLE,
    SERVICES, STEWARD, TARGET_NYM, TRUSTEE, TXN_METADATA,
    TXN_METADATA_SEQ_NO, TXN_METADATA_TIME, TXN_PAYLOAD, TXN_PAYLOAD_DATA,
    TXN_PAYLOAD_METADATA, TXN_PAYLOAD_METADATA_FROM, TXN_TYPE, VALIDATOR,
    VERKEY)
from plenum_tpu.common.exceptions import (
    InvalidClientRequest, UnauthorizedClientRequest)
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import (
    get_from, get_payload_data, get_seq_no, get_txn_time)
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.execution_lanes import TouchedKeys

from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")


class RequestHandler(ABC):
    def __init__(self, database_manager: DatabaseManager, txn_type: str,
                 ledger_id: Optional[int]):
        self.database_manager = database_manager
        self.txn_type = txn_type
        self.ledger_id = ledger_id
        self._ledger = None
        self._state = None

    @property
    def ledger(self):
        # memoized: the registry is fixed after node bootstrap, and this
        # property sits on the per-request apply path (2 dict hops per
        # access adds up at 25-node scale)
        ledger = self._ledger
        if ledger is None:
            ledger = self._ledger = \
                self.database_manager.get_ledger(self.ledger_id)
        return ledger

    @property
    def state(self):
        state = self._state
        if state is None:
            state = self._state = \
                self.database_manager.get_state(self.ledger_id)
        return state


class WriteRequestHandler(RequestHandler):
    @abstractmethod
    def static_validation(self, request: Request): ...

    @abstractmethod
    def dynamic_validation(self, request: Request, req_pp_time=None): ...

    @abstractmethod
    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False): ...

    def touched_keys(self, request: Request):
        """Declared state touches for the conflict-lane executor
        (server/execution_lanes.py): a ``TouchedKeys`` whose read/write
        sets are a SUPERSET of every ``state.get``/``state.set`` key
        this handler's ``dynamic_validation`` + ``update_state`` can
        reach for `request` — computable from the request alone, never
        from state content. Return None when the key set is inherently
        dynamic (whole-state scans, digest chains read from state):
        the request then takes the designated serial lane and is
        excluded from batched read prefetch. Lint rule PT011 flags
        state accesses not reachable from this declaration."""
        return None

    def apply_request(self, request: Request, batch_ts: int):
        """Default apply: reqToTxn + update_state; returns (start, txn)."""
        from plenum_tpu.common.txn_util import (append_txn_metadata, reqToTxn)
        txn = append_txn_metadata(reqToTxn(request), txn_time=batch_ts)
        self.update_state(txn, None, request)
        return txn


class ReadRequestHandler(RequestHandler):
    @abstractmethod
    def get_result(self, request: Request) -> dict: ...

    def make_state_proof(self, key: bytes, root: bytes) -> dict:
        """Structured state proof a client can verify against ONE node:
        {root_hash, proof_nodes, multi_signature?} — the multi-sig from
        the BlsStore is what lets the root itself be trusted without
        f+1 matching replies (reference
        handler_interfaces/read_request_handler.py:39-56: bls_store.get
        on the proof root → MULTI_SIGNATURE in the proof dict)."""
        from plenum_tpu.common.constants import (
            MULTI_SIGNATURE, PROOF_NODES, ROOT_HASH)
        from plenum_tpu.common.serializers.base58 import b58encode
        root_b58 = b58encode(bytes(root))
        proof = {
            ROOT_HASH: root_b58,
            PROOF_NODES: self.state.generate_state_proof(
                key, root=root, serialize=True),
        }
        bls_store = getattr(self.database_manager, "bls_store", None)
        if bls_store is not None:
            multi_sig = bls_store.get(root_b58)
            if multi_sig is not None:
                proof[MULTI_SIGNATURE] = multi_sig.as_dict()
        return proof

    def make_state_proof_batch(self, keys, root, with_values=False):
        """N-key batched form of make_state_proof: proof nodes for every
        key come from ONE state-engine call (level-wise device SHA3,
        shared spine loads — state/device_state.py) and the BLS
        multi-sig for the shared root resolves once, so a single node
        can serve proof-bearing reads at scale. Each returned dict is
        byte-identical to make_state_proof(key, root).

        with_values=True → (values, proof_dicts): the SAME single walk
        resolves every key's value (a proof walk finds it anyway), so
        read serving never pays a second batched walk for the data."""
        from plenum_tpu.common.constants import (
            MULTI_SIGNATURE, PROOF_NODES, ROOT_HASH)
        from plenum_tpu.common.serializers.base58 import b58encode
        root_b58 = b58encode(bytes(root))
        if with_values:
            values, serialized = self.state.get_with_proofs_batch(
                keys, root=root, serialize=True)
        else:
            values = None
            serialized = self.state.generate_state_proof_batch(
                keys, root=root, serialize=True)
        multi_sig_dict = None
        bls_store = getattr(self.database_manager, "bls_store", None)
        if bls_store is not None:
            multi_sig = bls_store.get(root_b58)
            if multi_sig is not None:
                multi_sig_dict = multi_sig.as_dict()
        out = []
        for nodes in serialized:
            proof = {ROOT_HASH: root_b58, PROOF_NODES: nodes}
            if multi_sig_dict is not None:
                # shallow copy: replies are serialized independently and
                # must not alias one mutable dict
                proof[MULTI_SIGNATURE] = dict(multi_sig_dict)
            out.append(proof)
        return (values, out) if with_values else out


class ActionRequestHandler(RequestHandler):
    """Non-ledger actions: validated and executed locally, no consensus
    (reference handler_interfaces/action_request_handler.py)."""

    def __init__(self, database_manager: DatabaseManager, txn_type: str):
        super().__init__(database_manager, txn_type, ledger_id=None)

    def static_validation(self, request: Request):
        pass

    def dynamic_validation(self, request: Request):
        pass

    @abstractmethod
    def process_action(self, request: Request) -> dict: ...


# --------------------------------------------------------------- helpers

# the leaf codec lives in common (clients rebuild proof leaves from it);
# re-exported here for the handler-side callers
from plenum_tpu.common.state_codec import (  # noqa: F401
    decode_state_value, encode_state_value, nym_to_state_key)


# ------------------------------------------------------------------- NYM

class NymHandler(WriteRequestHandler):
    """Reference: plenum/server/request_handlers/nym_handler.py — identity
    registration/rotation on the domain ledger."""

    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, NYM, DOMAIN_LEDGER_ID)
        # (head_root, state_key) → raw state value, carried from
        # dynamic_validation to the immediately following update_state so
        # the hot apply path walks the trie once per request, not twice
        self._lookup_memo = None
        # identifier → decoded nym record (or None), saving a trie walk
        # + JSON decode per request for repeat authors: author role
        # checks (dynamic validation) AND verkey resolution (client
        # authentication) both hit it, and in a loaded pool most
        # requests in a batch share a handful of authors. Exactly
        # invalidated: update_state pops the nym it writes; any state
        # rewind clears it wholesale (clear_caches)
        self._nym_cache: dict = {}

    def static_validation(self, request: Request):
        op = request.operation
        if not op.get(TARGET_NYM):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "NYM must have a dest")
        role = op.get(ROLE)
        if role not in (None, STEWARD, TRUSTEE):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "invalid role {}".format(role))

    def touched_keys(self, request: Request):
        """NYM touches exactly two keys, both computable from the
        request: the target nym's record (read in validation, written
        in update_state) and the author's record (role checks via
        cached_nym_record)."""
        dest = request.operation.get(TARGET_NYM)
        if not isinstance(dest, str) or not dest:
            return None
        key = nym_to_state_key(dest)
        reads = [(DOMAIN_LEDGER_ID, key)]
        idr = request.identifier
        if isinstance(idr, str) and idr:
            reads.append((DOMAIN_LEDGER_ID, nym_to_state_key(idr)))
        return TouchedKeys(reads=reads,
                           writes=((DOMAIN_LEDGER_ID, key),))

    def dynamic_validation(self, request: Request, req_pp_time=None):
        op = request.operation
        key = nym_to_state_key(op[TARGET_NYM])
        raw = self.state.get(key, isCommitted=False)
        # memo keyed by the state's mutation counter, NOT headHash —
        # reading headHash would force the write buffer to flush (and
        # hash) once per request, defeating the batched apply
        self._lookup_memo = (getattr(self.state, "mutation_count", None),
                             key, raw)
        existing, _, _ = decode_state_value(raw)
        is_creation = existing is None
        if is_creation:
            # new nym with a privileged role needs a privileged author
            if op.get(ROLE) in (STEWARD, TRUSTEE):
                author = self._author_role(request)
                if author != TRUSTEE:
                    raise UnauthorizedClientRequest(
                        request.identifier, request.reqId,
                        "only TRUSTEE can create {}".format(op.get(ROLE)))
        else:
            # key rotation: only the nym owner may change its verkey
            if VERKEY in op and request.identifier != op[TARGET_NYM]:
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only the owner can rotate a verkey")
            # role edits (promotion AND demotion) need a TRUSTEE author —
            # otherwise any authenticated client could grant itself
            # TRUSTEE (reference nym_handler dynamic auth rules)
            if ROLE in op and op.get(ROLE) != existing.get(ROLE):
                if self._author_role(request) != TRUSTEE:
                    raise UnauthorizedClientRequest(
                        request.identifier, request.reqId,
                        "only TRUSTEE can change a nym's role")

    _MISS = object()

    def cached_nym_record(self, identifier: str):
        """Decoded uncommitted-state record for a nym (None = absent),
        through the invalidation-exact cache."""
        rec = self._nym_cache.get(identifier, self._MISS)
        if rec is not self._MISS:
            return rec
        rec, _, _ = decode_state_value(self.state.get(
            nym_to_state_key(identifier), isCommitted=False))
        from plenum_tpu.common.config import Config
        if len(self._nym_cache) > Config.NYM_CACHE_MAX:
            self._nym_cache.clear()
        self._nym_cache[identifier] = rec
        return rec

    def _author_role(self, request: Request):
        idr = request.identifier
        if idr is None:
            return None
        return (self.cached_nym_record(idr) or {}).get(ROLE)

    def clear_caches(self):
        """State was rewound under us (batch revert / catchup): every
        cached read may now be stale."""
        self._nym_cache.clear()
        self._lookup_memo = None

    def invalidate_for_writes(self, state_keys):
        """Lane safety for the nym read cache: before a lane-planned
        batch applies, drop every cached record whose state key the
        batch DECLARES it will write. In-order apply already pops the
        written nym at each update_state, so this pre-invalidation is
        a structural guarantee, not a fix for a live bug: whatever
        order lanes resolve their reads in, a record the batch touches
        can never be served from a pre-batch cache entry. Keys that
        don't decode to an identifier clear the cache wholesale (the
        nym key codec is identifier.encode(); anything else means the
        caller's key space changed under us)."""
        for key in state_keys:
            try:
                self._nym_cache.pop(bytes(key).decode(), None)
            except UnicodeDecodeError:
                self._nym_cache.clear()
                return

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        payload = txn[TXN_PAYLOAD]
        data = payload[TXN_PAYLOAD_DATA]
        md = txn.get(TXN_METADATA) or {}
        seq_no = md.get(TXN_METADATA_SEQ_NO)
        nym = data[TARGET_NYM]
        key = nym_to_state_key(nym)
        memo = self._lookup_memo
        if memo is not None and memo[1] == key and \
                memo[0] == getattr(self.state, "mutation_count", object()):
            raw = memo[2]
        else:
            raw = self.state.get(key, isCommitted=False)
        existing, _, _ = decode_state_value(raw)
        value = dict(existing or {})
        value["identifier"] = payload[TXN_PAYLOAD_METADATA].get(
            TXN_PAYLOAD_METADATA_FROM)
        if ROLE in data:
            value[ROLE] = data[ROLE]
        if VERKEY in data:
            value[VERKEY] = data[VERKEY]
        value.setdefault("seqNo", seq_no)
        self.state.set(key, encode_state_value(
            value, seq_no, md.get(TXN_METADATA_TIME)))
        self._nym_cache.pop(nym, None)
        return value

    def get_nym_details(self, nym: str, is_committed=True):
        return decode_state_value(self.state.get(nym_to_state_key(nym),
                                                 isCommitted=is_committed))


# ------------------------------------------------------------------ NODE

class NodeHandler(WriteRequestHandler):
    """Pool membership: NODE txns add nodes / update services & keys.
    Reference: plenum/server/request_handlers/node_handler.py +
    pool_manager semantics."""

    def __init__(self, database_manager: DatabaseManager,
                 steward_provider=None):
        super().__init__(database_manager, NODE, POOL_LEDGER_ID)
        self._steward_provider = steward_provider
        # aliases seeded at pool construction without pool-ledger NODE
        # records (wired by the node owner): they have no state entry, so
        # without this a steward could "create" a NODE txn reusing a seed
        # alias and hijack/demote a validator it does not own
        self.reserved_aliases = lambda: set()

    def static_validation(self, request: Request):
        op = request.operation
        if not op.get(TARGET_NYM):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "NODE must have a dest")
        data = op.get(DATA)
        if not isinstance(data, dict) or not data.get("alias"):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "NODE data must include alias")
        services = data.get(SERVICES)
        if services is not None and (
                not isinstance(services, list)
                or any(s != VALIDATOR for s in services)):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "services must be a list drawn from ['{}']".format(
                    VALIDATOR))

    def touched_keys(self, request: Request):
        # inherently dynamic key set: alias uniqueness and steward
        # ownership scan the WHOLE pool state head (_committed_aliases /
        # _steward_owns_node), so the touched keys are a function of
        # state content, not of the request — NODE txns take the
        # serial lane (PT011 baseline records the scans as justified)
        return None

    def dynamic_validation(self, request: Request, req_pp_time=None):
        op = request.operation
        existing, _, _ = decode_state_value(self.state.get(
            nym_to_state_key(op[TARGET_NYM]), isCommitted=False))
        data = op.get(DATA, {})
        author_role = self._author_role(request)
        if existing is None:
            # new node: author must be a steward (reference node_handler
            # auth: pool membership writes are steward-gated), one node
            # per steward, alias must be unique
            if author_role not in (STEWARD, TRUSTEE):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only a STEWARD or TRUSTEE may add a node")
            if data.get("alias") in self.reserved_aliases() \
                    and author_role != TRUSTEE:
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "alias {} belongs to a genesis validator — only a "
                    "TRUSTEE may write its record".format(
                        data.get("alias")))
            if author_role == STEWARD and self._steward_owns_node(
                    request.identifier):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "steward already has a node")
            aliases = self._committed_aliases()
            if data.get("alias") in aliases:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "node alias {} already taken".format(data.get("alias")))
        else:
            # edits: only the owning steward or a TRUSTEE
            if author_role != TRUSTEE and \
                    request.identifier != existing.get("identifier"):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only the node's steward or a TRUSTEE may edit it")
            if data.get("alias") and \
                    data["alias"] != existing.get("alias"):
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "node alias cannot change")

    def _author_role(self, request: Request):
        """Author roles live in the DOMAIN state (nym registry)."""
        if request.identifier is None:
            return None
        domain_state = self.database_manager.get_state(DOMAIN_LEDGER_ID)
        if domain_state is None:
            return None
        val, _, _ = decode_state_value(domain_state.get(
            nym_to_state_key(request.identifier), isCommitted=False))
        return (val or {}).get(ROLE)

    def _steward_owns_node(self, steward_nym: str) -> bool:
        for key, value in self.state.head.items():
            val, _, _ = decode_state_value(value)
            if isinstance(val, dict) and \
                    val.get("identifier") == steward_nym:
                return True
        return False

    def _committed_aliases(self):
        aliases = set()
        for key, value in self.state.head.items():
            val, _, _ = decode_state_value(value)
            if isinstance(val, dict) and "alias" in val:
                aliases.add(val["alias"])
        return aliases

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        data = get_payload_data(txn)
        nym = data[TARGET_NYM]
        existing, _, _ = decode_state_value(
            self.state.get(nym_to_state_key(nym), isCommitted=False))
        value = dict(existing or {})
        value.update(data.get(DATA, {}))
        # record the owning steward on creation (edit authorization key)
        value.setdefault("identifier", get_from(txn))
        self.state.set(nym_to_state_key(nym),
                       encode_state_value(value, get_seq_no(txn),
                                          get_txn_time(txn)))
        return value


# ---------------------------------------------------------------- GET_TXN

class GetTxnHandler(ReadRequestHandler):
    """Reference: plenum/server/request_handlers/get_txn_handler.py."""

    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, GET_TXN, None)

    def get_result(self, request: Request) -> dict:
        op = request.operation
        lid = op.get("ledgerId", DOMAIN_LEDGER_ID)
        seq_no = op.get(DATA)
        ledger = self.database_manager.get_ledger(lid)
        if ledger is None:
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "unknown ledger {}".format(lid))
        txn = ledger.getBySeqNo(seq_no) if isinstance(seq_no, int) else None
        return {
            TXN_TYPE: GET_TXN,
            "identifier": request.identifier,
            "reqId": request.reqId,
            "seqNo": seq_no,
            "data": txn,
        }


# ------------------------------------------------------------------- NYM read

class GetNymHandler(ReadRequestHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, "105", DOMAIN_LEDGER_ID)

    def _resolve_root(self, request: Request):
        """Validate the operation and resolve the state root it reads:
        → (nym, state_key, root|None). Shared by the single and the
        batched serving paths so both answer identically."""
        nym = request.operation.get(TARGET_NYM)
        if not isinstance(nym, str) or not nym:
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "GET_NYM must have a dest")
        key = nym_to_state_key(nym)
        ts = request.operation.get("timestamp")
        if ts is not None and (isinstance(ts, bool)
                               or not isinstance(ts, (int, float))):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "timestamp must be a number")
        if ts is not None:
            # state-at-a-time: resolve the committed root at (or before)
            # the timestamp via the ts store; the MPT keeps history, so
            # old roots stay readable and provable (reference
            # state_ts_store + get_nym_handler timestamp path)
            ts_store = self.database_manager.get_store("state_ts")
            root = (ts_store.get_equal_or_prev(ts, self.ledger_id)
                    if ts_store is not None else None)
        else:
            # graceful read degradation: while the node recovers
            # (catchup / view change) reads keep serving the pinned
            # pre-recovery committed root — the newest root that still
            # has a BLS multi-sig — instead of the unsigned
            # intermediate roots catchup commits txn by txn
            root = self.database_manager.pinned_read_root(self.ledger_id)
            if root is None:
                root = self.state.committedHeadHash
        return nym, key, root

    @staticmethod
    def _assemble(request: Request, nym: str, value, proof) -> dict:
        data, seq_no, txn_time = decode_state_value(value)
        return {
            TXN_TYPE: "105",
            "identifier": request.identifier,
            "reqId": request.reqId,
            "dest": nym,
            "data": data,
            "seqNo": seq_no,
            # the client re-encodes (data, seqNo, txnTime) to check the
            # proof leaf byte-for-byte — the time must travel with it
            "txnTime": txn_time,
            "state_proof": proof,
        }

    def get_result(self, request: Request) -> dict:
        nym, key, root = self._resolve_root(request)
        if root is None:
            value, proof = None, None
        else:
            value = self.state.get_for_root_hash(root, key)
            proof = self.make_state_proof(key, root)
        return self._assemble(request, nym, value, proof)

    def get_results_batch(self, requests) -> list:
        """Serve MANY GET_NYMs at once: requests reading the same root
        (the common case — every current-state read shares the
        committed root) resolve their values and their proofs through
        ONE batched state-engine walk each (make_state_proof_batch),
        with the BLS multi-sig looked up once per root. Per-request
        validation failures come back as exception instances in the
        result slots, so one bad request never fails the batch."""
        out: list = [None] * len(requests)
        by_root: dict = {}
        for i, request in enumerate(requests):
            try:
                nym, key, root = self._resolve_root(request)
            except InvalidClientRequest as e:
                out[i] = e
                continue
            if root is None:
                out[i] = self._assemble(request, nym, None, None)
            else:
                by_root.setdefault(bytes(root), []).append(
                    (i, request, nym, key))
        for root, items in by_root.items():
            keys = [key for _, _, _, key in items]
            # ONE walk serves both the values and the proofs
            values, proofs = self.make_state_proof_batch(
                keys, root, with_values=True)
            for (i, request, nym, _), value, proof in zip(items, values,
                                                          proofs):
                out[i] = self._assemble(request, nym, value, proof)
        return out
