"""TxnPoolManager — pool-ledger-driven live membership.

Reference: plenum/server/pool_manager.py:440 (TxnPoolManager: node
add/demote through NODE txns reconfigures the running pool) and
plenum/server/node.py:1260 (adjustReplicas: instance count follows f).

Committed NODE txns are the single source of truth for membership: every
node replays the same pool ledger, so every node derives the same
validator list (ctor seed + ledger order) and the same quorums. Applying
a change touches: every protocol instance's shared data (validators +
Quorums), the primary selectors (future views only — the CURRENT
primary never silently moves, matching the reference's view-stable
primaries), the Replicas collection (f+1 instances), the Propagator's
quorum, and — when the node runs a real transport — the connection set
via the owner's callback.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

from plenum_tpu.common.constants import (
    ALIAS, DATA, NODE, POOL_LEDGER_ID, SERVICES, TARGET_NYM, VALIDATOR)
from plenum_tpu.common.txn_util import get_payload_data, get_type

logger = logging.getLogger(__name__)


class TxnPoolManager:
    def __init__(self, initial_validators: List[str], db_manager,
                 on_change: Callable[[List[str]], None] = None):
        """on_change(new_validators) fires AFTER the validator list
        actually changed (never during construction/rescan — the owner
        reads .validators at build time instead)."""
        self._db = db_manager
        self._on_change = on_change or (lambda v: None)
        # alias order is consensus-critical (primary rotation indexes
        # into it): ctor seed order, then pool-ledger commit order
        self._order: List[str] = list(initial_validators)
        self.seed_aliases = frozenset(initial_validators)
        self._info: Dict[str, dict] = {
            alias: {SERVICES: [VALIDATOR]} for alias in initial_validators}
        self._rescan()

    # ---------------------------------------------------------- registry

    @property
    def validators(self) -> List[str]:
        return [alias for alias in self._order
                if VALIDATOR in self._info[alias].get(SERVICES, [])]

    def node_info(self, alias: str) -> Optional[dict]:
        return self._info.get(alias)

    def _rescan(self):
        """Replay all committed pool-ledger NODE txns (node start /
        restart; the ledger includes genesis)."""
        ledger = self._db.get_ledger(POOL_LEDGER_ID)
        if ledger is None:
            return
        for _, txn in ledger.getAllTxn():
            if get_type(txn) == NODE:
                self._apply_payload(get_payload_data(txn))

    def _apply_payload(self, payload: dict) -> bool:
        """Fold one NODE txn payload into the registry. → membership
        changed (validator added/removed)."""
        data = payload.get(DATA) or {}
        alias = data.get(ALIAS)
        if not alias:
            return False
        before = self.validators
        # a NODE txn that omits SERVICES must NOT default to validator —
        # only an explicit services grant changes membership (ctor-seeded
        # aliases keep their [VALIDATOR] default)
        info = self._info.setdefault(alias, {SERVICES: []})
        if alias not in self._order:
            self._order.append(alias)
        if TARGET_NYM in payload:
            info["dest"] = payload[TARGET_NYM]
        for key, value in data.items():
            if key != ALIAS:
                info[key] = value
        return self.validators != before

    # ------------------------------------------------------------- hooks

    def process_committed_txn(self, txn: dict):
        """Owner feeds every committed (or caught-up) pool-ledger txn."""
        if get_type(txn) != NODE:
            return
        if self._apply_payload(get_payload_data(txn)):
            logger.info("pool membership changed: validators=%s",
                        self.validators)
            self._on_change(self.validators)
