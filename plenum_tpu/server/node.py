"""Node — the consensus-node orchestrator.

Reference: plenum/server/node.py:129 (3,242 LoC god object) — rebuilt lean:
storage bootstrap (NodeBootstrap, node_bootstrap.py:17), client request
intake (processRequest :2000), propagation (processPropagate :2099),
execution (executeBatch :2661 via NodeBatchExecutor), and replies.

The node speaks to peers through ONE ExternalBus (SimNetwork in tests, a
socket transport in deployment) and to clients through a reply callback —
no sockets in this class, so the whole node is deterministic under
MockTimer (SURVEY.md §4 rung 3 without processes).

Client write path (SURVEY.md §3.3): REQUEST → authenticate (TPU-batched
ed25519 via CoreAuthNr) → PROPAGATE → quorum f+1 finalise → ordering
queues → 3PC → Ordered → commit (ledger merkle append + MPT commit +
audit txn) → Reply{txn + audit path} to client.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from plenum_tpu.common.config import Config
from plenum_tpu.common.constants import (
    AUDIT_LEDGER_ID, BLS_KEY, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID, GET_TXN,
    NODE, NYM, POOL_LEDGER_ID, VERKEY)
from plenum_tpu.common.exceptions import InvalidClientMessageException
from plenum_tpu.common.messages.client_request import ClientMessageValidator
from plenum_tpu.common.messages.message_factory import node_message_factory
from plenum_tpu.common.messages.node_messages import (
    Commit, FlatBatch, Ordered, Prepare, PrePrepare, Propagate,
    PropagateBatch, Reject, Reply, RequestAck, RequestNack, ThreePCBatch)
from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import (
    get_payload_data, get_seq_no, get_txn_time)
from plenum_tpu.consensus.ordering_service import Suspicions
from plenum_tpu.consensus.replica_service import ReplicaService
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.runtime.timer import TimerService
from plenum_tpu.server.batch_handlers import (
    AuditBatchHandler, ConfigBatchHandler, DomainBatchHandler,
    PoolBatchHandler, TsStoreBatchHandler)
from plenum_tpu.server.client_authn import CoreAuthNr, ReqAuthenticator
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.executor import NodeBatchExecutor
from plenum_tpu.server.propagator import Propagator
from plenum_tpu.server.request_handlers import (
    GetNymHandler, GetTxnHandler, NodeHandler, NymHandler,
    decode_state_value, nym_to_state_key)
from plenum_tpu.server.write_request_manager import (
    ActionRequestManager, ReadRequestManager, WriteRequestManager)
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.native import try_load_ext
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory

_fp = try_load_ext("fastpath")
from plenum_tpu.observability.tracing import (
    CAT_3PC, CAT_DEVICE, CAT_INTAKE, CAT_PROPAGATE, CAT_RECOVERY,
    CAT_REPLY, NullTracer, Tracer)
from plenum_tpu.observability.telemetry import (
    TM, NullTelemetryHub, TelemetryHub, get_seam_hub)
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector

logger = logging.getLogger(__name__)


class NodeBootstrap:
    """Storage + handler registry init (reference node_bootstrap.py:17)."""

    @staticmethod
    def make_tree_hasher(config: Optional[Config] = None):
        """TreeHasher wired to the batched JAX SHA-256 kernel above the
        config threshold (the production path for bulk ledger recovery,
        catchup verification and 1M-leaf proof batches — SURVEY §2.9
        sha256 obligation); hashlib handles the scalar floor."""
        from plenum_tpu.ledger.tree_hasher import TreeHasher
        config = config or Config()
        if config.SHA256_BACKEND != "jax":
            return TreeHasher()
        from plenum_tpu.ops.sha256 import get_default_backend
        return TreeHasher(batch_backend=get_default_backend(),
                          batch_threshold=config.SHA256_BATCH_THRESHOLD)

    @staticmethod
    def init_storage(storage_factory=None,
                     config: Optional[Config] = None) -> DatabaseManager:
        make_kv = storage_factory or (lambda name: KeyValueStorageInMemory())
        conf = config or Config()
        dm = DatabaseManager()
        for lid, name in ((POOL_LEDGER_ID, "pool"),
                          (DOMAIN_LEDGER_ID, "domain"),
                          (CONFIG_LEDGER_ID, "config"),
                          (AUDIT_LEDGER_ID, "audit")):
            ledger = Ledger(txn_store=make_kv(name + "_ledger"),
                            tree_hasher=NodeBootstrap.make_tree_hasher(
                                config))
            if conf.MERKLE_DEVICE_PROOFS and conf.SHA256_BACKEND == "jax":
                # large reply/catchup proof batches route to the
                # device-resident tree; below MERKLE_DEVICE_PROOF_MIN
                # the host memo path keeps winning and nothing changes
                ledger.tree.attach_device_engine(
                    proof_min=conf.MERKLE_DEVICE_PROOF_MIN,
                    chunk=conf.MERKLE_DEVICE_PROOF_CHUNK,
                    pipeline_depth=conf.MERKLE_DEVICE_PIPELINE_DEPTH,
                    warm=True)  # recovered ledgers sync off the hot path
            state = None
            if lid != AUDIT_LEDGER_ID:
                state = PruningState(make_kv(name + "_state"))
                if conf.STATE_DEVICE_ENGINE:
                    # batched multi-key gets, whole-batch applies and
                    # N-key proof generation route to the device MPT
                    # engine; below STATE_DEVICE_BATCH_MIN the host
                    # trie keeps winning and nothing changes. Warm
                    # once (the SHA3 kernels are process-wide) so the
                    # first serving batch skips the jit compile.
                    state.attach_device_engine(
                        batch_min=conf.STATE_DEVICE_BATCH_MIN,
                        warm=(lid == DOMAIN_LEDGER_ID))
            dm.register_new_database(lid, ledger, state,
                                     taa_acceptance_required=(
                                         lid == DOMAIN_LEDGER_ID))
        from plenum_tpu.storage.state_ts_store import StateTsStore
        dm.register_new_store("state_ts", StateTsStore(make_kv("state_ts")))
        return dm

    @staticmethod
    def init_managers(dm: DatabaseManager, config: Optional[Config] = None
                      ) -> Tuple[WriteRequestManager, ReadRequestManager]:
        from plenum_tpu.server.taa_handlers import (
            GetTxnAuthorAgreementAmlHandler, GetTxnAuthorAgreementHandler,
            TaaAcceptanceValidator, TxnAuthorAgreementAmlHandler,
            TxnAuthorAgreementDisableHandler, TxnAuthorAgreementHandler)
        wm = WriteRequestManager(dm)
        wm.register_req_handler(NymHandler(dm))
        wm.register_req_handler(NodeHandler(dm))
        from plenum_tpu.server.freeze_handlers import (
            GetFrozenLedgersHandler, LedgersFreezeHandler)
        wm.register_req_handler(TxnAuthorAgreementHandler(dm))
        wm.register_req_handler(TxnAuthorAgreementAmlHandler(dm))
        wm.register_req_handler(TxnAuthorAgreementDisableHandler(dm))
        wm.register_req_handler(LedgersFreezeHandler(dm))
        wm.taa_validator = TaaAcceptanceValidator(dm, config or Config())
        wm.register_batch_handler(PoolBatchHandler(dm))
        wm.register_batch_handler(DomainBatchHandler(dm))
        wm.register_batch_handler(ConfigBatchHandler(dm))
        wm.register_batch_handler(TsStoreBatchHandler(dm))
        wm.register_batch_handler(AuditBatchHandler(dm))
        rm = ReadRequestManager()
        rm.register_req_handler(GetTxnHandler(dm))
        rm.register_req_handler(GetNymHandler(dm))
        rm.register_req_handler(GetTxnAuthorAgreementHandler(dm))
        rm.register_req_handler(GetTxnAuthorAgreementAmlHandler(dm))
        rm.register_req_handler(GetFrozenLedgersHandler(dm))
        return wm, rm


class Node:
    def __init__(self, name: str, validators: List[str],
                 timer: TimerService, network,
                 config: Optional[Config] = None,
                 storage_factory=None,
                 client_reply_handler: Callable[[str, object], None] = None,
                 bls_bft_replica=None, bls_signer=None,
                 genesis_txns: Optional[List[dict]] = None,
                 on_membership_change: Callable[[List[str]], None] = None,
                 metrics=None, tracer=None):
        """network: ExternalBus to peers; client_reply_handler(client_id,
        msg) delivers Acks/Nacks/Replies back to clients."""
        from plenum_tpu.server.observer import Observable
        self.name = name
        self.config = config or Config()
        self.metrics = metrics or NullMetricsCollector()
        # flight recorder (observability/): one ring-buffer tracer per
        # node, injected into every instrumented stage below so a 3PC
        # batch's whole lifecycle lands in one per-node buffer that the
        # sim pool / trace_view merges into a pool-wide timeline
        if tracer is None:
            tracer = Tracer(name=name,
                            capacity=self.config.TRACING_BUFFER_SPANS) \
                if self.config.TRACING_ENABLED else NullTracer(name)
        self.tracer = tracer
        # always-on telemetry plane (observability/telemetry.py): one
        # hub per node — latency histograms on the ordered money path,
        # pool-health gauges, recovery counters. Device-seam lane
        # accounting lands in the process-wide seam hub instead (the
        # seams are shared across co-resident nodes, like the mesh).
        self.telemetry = TelemetryHub(name=name) \
            if getattr(self.config, "TELEMETRY_ENABLED", True) \
            else NullTelemetryHub(name)
        # digest → intake-accept perf_counter: start marks for the
        # intake→reply latency histogram (popped at commit/reject/GC;
        # capped by TELEMETRY_PENDING_MAX)
        self._tm_intake_ts: Dict[str, float] = {}
        # GC pause/throughput feed (reference gc_trackers.py): one
        # process-wide hook, weakly attached — only worth the callback
        # when a real collector will persist it
        if metrics is not None:
            from plenum_tpu.utils.gc_tracker import GcTimeTracker
            GcTimeTracker.instance().attach(self.metrics)
        self.observable = Observable()
        self.timer = timer
        self.network = network
        self._reply_to_client = client_reply_handler or (lambda c, m: None)
        # without a client transport there is nobody to reply to — skip
        # building Reply payloads (txn + b58 audit path) entirely
        self._clients_attached = client_reply_handler is not None

        # ---- storage + execution pipeline
        self.db_manager = NodeBootstrap.init_storage(storage_factory,
                                                     self.config)
        self.write_manager, self.read_manager = \
            NodeBootstrap.init_managers(self.db_manager, self.config)
        self.action_manager = ActionRequestManager()

        # ---- genesis (skipped on restart: the persisted ledgers already
        # contain it) — must precede membership derivation, which reads
        # the pool ledger
        if genesis_txns and all(
                self.db_manager.get_ledger(lid).size == 0
                for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID,
                            CONFIG_LEDGER_ID)):
            self._load_genesis(genesis_txns)

        # ---- live pool membership (reference TxnPoolManager): the ctor
        # list seeds the registry; committed NODE txns evolve it
        from plenum_tpu.server.pool_manager import TxnPoolManager
        self.pool_manager = TxnPoolManager(
            validators, self.db_manager,
            on_change=self._on_validators_changed)
        self._on_membership_change = on_membership_change
        validators = self.pool_manager.validators
        # ctor-seeded validators have no pool-state NODE record; their
        # aliases are reserved so a steward cannot hijack them
        node_handler = self.write_manager.request_handlers.get(NODE)
        if node_handler is not None:
            node_handler.reserved_aliases = \
                lambda: self.pool_manager.seed_aliases

        # ---- client authentication (TPU-batched seam); the provider is
        # config-selected: in-process device batching by default, or the
        # host verify daemon in multi-process deployments
        provider = getattr(self.config, "VERIFIER_PROVIDER", "adaptive")
        verifier = None
        if provider:
            from plenum_tpu.crypto.batch_verifier import create_verifier
            kwargs = {}
            if provider == "remote":
                kwargs["addr"] = (self.config.VERIFIER_DAEMON_HOST,
                                  self.config.VERIFIER_DAEMON_PORT)
            elif provider in ("adaptive", "tpu_hub"):
                kwargs["threshold"] = getattr(
                    self.config, "VERIFIER_BATCH_THRESHOLD", None)
            verifier = create_verifier(provider, **kwargs)
        # apply this node's MESH_* knobs to the process-wide device-mesh
        # dispatcher (ops/mesh.py) that the verify/BLS/merkle seams
        # consult — import never initializes a backend
        from plenum_tpu.ops import mesh as _mesh_mod
        _mesh_mod.configure_from(self.config)
        self.authnr = CoreAuthNr(
            verkey_provider=self._verkey_from_domain_state,
            verifier=verifier)
        self.req_authenticator = ReqAuthenticator()
        self.req_authenticator.register_authenticator(self.authnr)

        # digest → pp_seq_no of the speculative batch that rejected it;
        # freed once that batch is at or below a stable checkpoint
        self._rejected_digests: Dict[str, int] = {}
        # ---- dedup index: payload_digest → (ledger_id, seqNo); rides the
        # same storage factory as the ledgers so it survives restarts
        # (reference loadSeqNoDB node.py:698)
        make_kv = storage_factory or (
            lambda _name: KeyValueStorageInMemory())
        self.seq_no_db = make_kv("seq_no_db")
        # node status DB: non-ledger runtime state that must survive a
        # restart — currently the backup primary's last sent PrePrepare
        # (reference nodeStatusDB, node.py loadNodeStatusDB)
        self.node_status_db = make_kv("node_status_db")
        from plenum_tpu.server.last_sent_pp_store import LastSentPpStoreHelper
        self.last_sent_pp_store = LastSentPpStoreHelper(self.node_status_db)
        # digest → client id awaiting reply
        self._req_clients: Dict[str, str] = {}

        # ---- consensus replica (master instance)
        from plenum_tpu.consensus.primary_selector import (
            RoundRobinConstantNodesPrimariesSelector)
        self._primary_selector = RoundRobinConstantNodesPrimariesSelector(
            validators)
        self.executor = NodeBatchExecutor(
            self.write_manager,
            requests_source=self._get_finalised_request,
            get_view_no=lambda: self.replica.view_no,
            primaries_for_view=self._primaries_for_batch,
            get_pp_seq_no=lambda:
                self.replica.ordering._last_applied_seq + 1,
            on_batch_committed=self._on_batch_committed,
            on_request_rejected=self._on_request_rejected,
            fused_dispatch=getattr(self.config, "FUSED_BATCH_DISPATCH",
                                   True),
            # the authnr's verifier may have a whole intake generation
            # queued — flush it into the fused window so the device
            # verifies while the host applies
            device_kick=lambda: self.authnr.flush(),
            # conflict-lane execution (docs/execution.md): declared-key
            # lane planning + batched read prefetch + merged hash
            # resolution per applied batch
            lanes=getattr(self.config, "EXEC_LANES", True),
            lane_min=getattr(self.config, "EXEC_LANE_MIN", None))
        # ---- freshness: stale ledgers get empty batches so BLS-signed
        # state roots never age past the timeout (reference
        # replica_freshness_checker.py)
        from plenum_tpu.consensus.freshness_checker import FreshnessChecker
        self.freshness_checker = None
        if (self.config.UPDATE_STATE_FRESHNESS
                and self.config.STATE_FRESHNESS_UPDATE_INTERVAL > 0):
            self.freshness_checker = FreshnessChecker(
                self.config.STATE_FRESHNESS_UPDATE_INTERVAL)
            for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID):
                self.freshness_checker.register_ledger(
                    lid, timer.get_current_time())

        # ---- BLS: a signer is enough to stand up the full BLS-BFT seam
        # (keys of peers come from pool-ledger NODE txns via the pool
        # manager; the aggregated multi-sigs land in a persistent store
        # that read handlers attach to state proofs)
        if bls_bft_replica is None and bls_signer is not None:
            from plenum_tpu.consensus.bls_bft_replica import (
                BlsBftReplica, BlsKeyRegister, BlsStore)
            from plenum_tpu.crypto.bls import BlsCryptoVerifierPlenum
            pool_state = self.db_manager.get_state(POOL_LEDGER_ID)
            bls_bft_replica = BlsBftReplica(
                name, bls_signer, BlsCryptoVerifierPlenum(),
                BlsKeyRegister(lambda n: (self.pool_manager.node_info(n)
                                          or {}).get(BLS_KEY)),
                bls_store=BlsStore(make_kv("bls_store")),
                get_pool_root=lambda: pool_state.committedHeadHash_b58
                if pool_state is not None else "",
                defer_share_verify=getattr(
                    self.config, "BLS_DEFER_SHARE_VERIFY", True))
        self.bls_bft_replica = bls_bft_replica
        if bls_bft_replica is not None:
            self.db_manager.bls_store = bls_bft_replica.bls_store
            # pay the key-dependent verifier setup now (subgroup checks,
            # prepared pairings), not on the first state-proof verify
            bls_bft_replica.warm_pool_keys(validators)

        self.replica = ReplicaService(
            name, validators, timer, network, executor=self.executor,
            config=self.config, bls_bft_replica=bls_bft_replica,
            checkpoint_digest_source=self._audit_root_at,
            freshness_checker=self.freshness_checker,
            # IC votes persist to nodeStatusDB (reference
            # instance_change_provider): restart keeps fresh votes
            vc_vote_store=self.node_status_db)

        # ---- RBFT redundant instances: f backups benchmark the master
        from plenum_tpu.server.replicas import (
            BackupInstanceFaultyProcessor, Replicas)
        self.replicas = Replicas(
            name, validators, timer, network, master=self.replica,
            config=self.config,
            on_backup_ordered=self._on_backup_ordered,
            on_backup_pp_sent=self.last_sent_pp_store.store_last_sent)

        # ---- columnar 3PC wire path: every instance's broadcast votes
        # coalesce into ONE THREE_PC_BATCH per tick (flushed at the end
        # of service()); inbound envelopes route into the columnar
        # process_*_batch intake per instance. Incoming batches are
        # always understood (peers may coalesce regardless of our own
        # sending config).
        from plenum_tpu.server.three_pc_outbox import ThreePCOutbox
        self._outbox_3pc = None
        self._outbox_flush_armed = False
        flat_wire_on = getattr(self.config, "FLAT_WIRE", True)
        if getattr(self.config, "THREE_PC_BATCH_WIRE", True):
            self._outbox_3pc = ThreePCOutbox(
                network, msg_len_limit=self.config.MSG_LEN_LIMIT,
                flat_wire_enabled=flat_wire_on)
            self.replicas.set_outbox(self._outbox_3pc)
        network.subscribe(ThreePCBatch, self._process_three_pc_batch)
        # flat zero-copy envelopes are always understood, whatever our
        # own sending config (peers choose their wire independently)
        network.subscribe(FlatBatch, self._process_flat_batch)

        # ---- propagation
        # gate for peer-relayed requests (client-intake requests were
        # authenticated at intake): a node must not vote for content
        # whose client signature it cannot verify. Deliberately a LOCAL
        # verifier, not self.authnr's configured provider — a remote or
        # device-batched provider would block (or deadlock) the prod
        # loop for what is a low-volume synchronous check.
        propagate_authnr = CoreAuthNr(
            verkey_provider=self._verkey_from_domain_state)

        def authenticate_propagated(request) -> bool:
            try:
                propagate_authnr.authenticate(request)
                return True
            except Exception:
                return False

        self.propagator = Propagator(
            name, self.replica.data.quorums, network,
            forward_handler=self._forward_finalised,
            authenticator=authenticate_propagated,
            forward_batch_handler=self._forward_finalised_batch,
            flat_wire_enabled=flat_wire_on)
        network.subscribe(Propagate, self.propagator.process_propagate)
        network.subscribe(PropagateBatch,
                          self.propagator.process_propagate_batch)

        self._validator = ClientMessageValidator()

        # ---- plugin seams: notifier event push + typed plugins
        # (reference notifier_plugin_manager.py:24, plugin_loader.py:25)
        from plenum_tpu.server.plugins import (
            PLUGIN_TYPE_STATS_CONSUMER, PLUGIN_TYPE_VERIFICATION,
            NotifierPluginManager, PluginLoader)
        self.notifier = NotifierPluginManager(
            node_name=name,
            enabled=self.config.NOTIFIER_EVENTS_ENABLED,
            spike_configs=self.config.SPIKE_EVENT_TRIGGERING
            if self.config.SPIKE_EVENTS_ENABLED else None)
        if self.config.NOTIFIER_PLUGINS_DIR:
            self.notifier.load_from_dir(self.config.NOTIFIER_PLUGINS_DIR)
        self.plugin_loader = None
        self._verification_plugins: List = []
        self._stats_plugins: List = []
        if self.config.PLUGINS_DIR:
            self.plugin_loader = PluginLoader(self.config.PLUGINS_DIR)
            self._verification_plugins = self.plugin_loader.get(
                PLUGIN_TYPE_VERIFICATION)
            self._stats_plugins = self.plugin_loader.get(
                PLUGIN_TYPE_STATS_CONSUMER)
        self._request_spike_accum = 0

        # ---- performance + primary-connection monitoring
        from plenum_tpu.common.messages.internal_messages import (
            NewViewAccepted, VoteForViewChange)
        from plenum_tpu.runtime.timer import RepeatingTimer
        from plenum_tpu.server.monitor import (
            Monitor, PrimaryConnectionMonitorService)
        self.monitor = Monitor(name, timer, self.replica.internal_bus,
                               config=self.config)
        # one collector, injected into every instrumented stage so the
        # per-stage breakdown (scripts/metrics_stats) covers the whole
        # money path with a single flush point
        for _staged in (self.propagator, self.executor, self.monitor,
                        self.replica.ordering, bls_bft_replica,
                        self.write_manager,
                        getattr(self.replica, "view_changer", None),
                        getattr(self.replica, "vc_trigger", None)):
            if _staged is not None:
                _staged.metrics = self.metrics
        self.db_manager.metrics = self.metrics
        # same single-injection-point pattern for the flight recorder:
        # every traced seam records into THIS node's ring buffer (the
        # view changer's recovery lane — view_change_start/done,
        # vc_timeout_escalated — rides along; the leecher is attached
        # after construction below)
        for _traced in (self.propagator, self.executor, self.replica,
                        self.replica.ordering, bls_bft_replica,
                        self._outbox_3pc,
                        getattr(self.replica, "view_changer", None)):
            if _traced is not None:
                _traced.tracer = self.tracer
        # journey plane: outgoing envelopes carry an advisory causal
        # stamp only when this node is traced AND the config gate is on
        # — an untraced node has no buffers for journeys to join, so
        # stamping it would be pure wire bytes
        _trace_ctx = bool(getattr(self.config, "TRACE_CONTEXT_ENABLED",
                                  True)) \
            and getattr(self.tracer, "enabled", False)
        self.propagator.trace_context = _trace_ctx
        if self._outbox_3pc is not None:
            self._outbox_3pc.trace_context = _trace_ctx
            self._outbox_3pc.origin = name
        # telemetry rides the same single-injection-point pattern: the
        # executor times the execute/fused-dispatch stages, the
        # ordering service the 3PC stage, the view changer counts
        # recovery events — all into THIS node's hub
        for _tm_staged in (self.executor, self.replica.ordering,
                           getattr(self.replica, "view_changer", None)):
            if _tm_staged is not None:
                _tm_staged.telemetry = self.telemetry
        if verifier is not None and hasattr(verifier, "tracer"):
            # device-dispatch profiling inside the CoalescingVerifierHub
            # (a hub shared across co-resident nodes keeps whichever
            # tracer was attached last — one buffer still sees every
            # fused launch)
            verifier.tracer = self.tracer
        if getattr(self.tracer, "enabled", False):
            # mesh_dispatch spans + per-device counters land in the same
            # buffer (process-wide mesh: last tracer attached wins, like
            # the shared hub above)
            _mesh_mod.get_mesh().tracer = self.tracer
        # state_get / state_apply / state_proof spans from the device
        # MPT engines land in this node's buffer too
        for _lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID):
            _state = self.db_manager.get_state(_lid)
            if _state is not None and \
                    getattr(_state, "_engine", None) is not None:
                _state._engine.tracer = self.tracer
        self.primary_connection_monitor = PrimaryConnectionMonitorService(
            self.replica.data, timer, self.replica.internal_bus, network,
            config=self.config)
        self.replica.internal_bus.subscribe(
            NewViewAccepted, lambda msg: self.monitor.reset())
        # the ordering pause during a view change must not read as
        # primary freshness-negligence right after it
        self.replica.internal_bus.subscribe(
            NewViewAccepted,
            lambda msg: self.freshness_checker is not None
            and self.freshness_checker.reset_all(
                self.timer.get_current_time()))
        # a new view invalidates any stored backup-primary position
        self.replica.internal_bus.subscribe(
            NewViewAccepted,
            lambda msg: self.last_sent_pp_store.erase_last_sent())
        from plenum_tpu.common.messages.internal_messages import (
            CheckpointStabilized)
        self.replica.internal_bus.subscribe(
            CheckpointStabilized, self._gc_rejected)

        def _check_master_degraded():
            if self.mode_participating and self.monitor.is_master_degraded():
                self.monitor.reset()
                self.notifier.send_cluster_degraded()
                self.replica.internal_bus.send(
                    VoteForViewChange(suspicion="MASTER_DEGRADED"))
        self._degradation_timer = RepeatingTimer(
            timer, self.config.ThroughputWindowSize,
            _check_master_degraded)
        # telemetry flush: sample pool-health gauges, append a flush
        # sample (the Perfetto counter-track time axis), and write the
        # per-node Prometheus exposition file when a directory is
        # configured. Fixed cadence is correct here (periodic non-retry
        # work), period single-sourced from Config.
        self._telemetry_timer = None
        if self.telemetry.enabled:
            self._telemetry_timer = RepeatingTimer(
                timer,
                getattr(self.config, "TELEMETRY_FLUSH_INTERVAL_S", 10),
                self._flush_telemetry)
        # periodic spike sampling + stats-consumer push (reference
        # node.py:2552 checkNodeRequestSpike / monitor.py:643
        # checkPerformance), only scheduled when someone listens
        self._spike_timer = None
        if self.config.SPIKE_EVENTS_ENABLED or self._stats_plugins:
            self._spike_timer = RepeatingTimer(
                timer, self.config.SPIKE_EVENTS_FREQ, self._sample_spikes)
        from plenum_tpu.server.replicas import BackupInstanceFaultyProcessor
        self.backup_faulty_processor = BackupInstanceFaultyProcessor(
            self.replicas, self.monitor, self.config)
        self._backup_faulty_timer = RepeatingTimer(
            timer, 4 * self.config.ThroughputWindowSize,
            self.backup_faulty_processor.check)

        # ---- catchup (leecher + seeder)
        from plenum_tpu.common.messages.internal_messages import (
            NeedMasterCatchup)
        from plenum_tpu.server.catchup import (
            NodeLeecherService, SeederService)
        self.seeder = SeederService(
            self.db_manager, network, name=name,
            view_source=lambda: (self.replica.view_no,
                                 self.replica.data.last_ordered_3pc[1]),
            config=self.config)
        self.leecher = NodeLeecherService(
            self.db_manager, network, timer,
            quorums_source=lambda: self.replica.data.quorums,
            on_catchup_txn=self._on_catchup_txn,
            on_finished=self._on_catchup_finished,
            config=self.config, name=name,
            # catchup evidence only counts from current validators the
            # node has not blacklisted: an unknown sender must not pad
            # status/cons-proof quorums or feed reps (the blacklister is
            # constructed below; the lambda dereferences at call time)
            peer_ok=lambda frm: (
                frm in self.pool_manager.validators
                and not self.blacklister.is_blacklisted(frm)))
        self.leecher.tracer = self.tracer
        self.replica.internal_bus.subscribe(
            NeedMasterCatchup, lambda msg: self.start_catchup())
        # graceful read degradation half 2: ordering pauses for the
        # whole view change, so proof-bearing reads pin the last
        # committed (BLS-signed) roots until the new view lands —
        # catchup pins/unpins the same way in start_catchup /
        # _on_catchup_finished
        from plenum_tpu.common.messages.internal_messages import (
            ViewChangeStarted)
        self.replica.internal_bus.subscribe(
            ViewChangeStarted,
            lambda msg: self.db_manager.pin_read_roots())
        self.replica.internal_bus.subscribe(
            NewViewAccepted,
            lambda msg: self.leecher.in_progress
            or self.db_manager.unpin_read_roots())

        # ---- suspicion reporting + blacklisting (reference
        # reportSuspiciousNode + SimpleBlacklister): every suspicion is
        # logged and counted; auto-blacklisting is opt-in and limited to
        # sender-attributable evidence — see server/blacklister.py
        from plenum_tpu.common.messages.internal_messages import (
            RaisedSuspicion)
        from plenum_tpu.server.blacklister import SimpleBlacklister
        self.blacklister = SimpleBlacklister(name)

        def on_suspicion(msg: RaisedSuspicion):
            ex = msg.ex
            if getattr(ex, "node", None):
                self.blacklister.report_suspicion(
                    ex.node, getattr(ex, "code", None),
                    getattr(ex, "reason", ""),
                    auto_blacklist=self.config.BLACKLIST_ON_SUSPICION)
        self.replicas.subscribe_suspicions(on_suspicion)

        orig_incoming = network.process_incoming

        def filtering_incoming(msg, frm):
            # connection state events must pass — monitors track peers
            # whether blacklisted or not
            if not isinstance(msg, (network.Connected,
                                    network.Disconnected)) \
                    and self.blacklister.is_blacklisted(frm):
                return None
            result = orig_incoming(msg, frm)
            # votes provoked by inbound deliveries (PREPAREs for landed
            # PPs, COMMITs on fresh quorums) accumulate in the outbox
            # until the next prod tick's flush in service(). Flushing
            # per delivery here was measured to defeat coalescing
            # entirely: each instance's PP arrives from a DIFFERENT
            # primary node, so every provoked vote shipped alone (18
            # singles per node per 3PC round at 25 validators, 0
            # envelopes). The deferred flush below only covers the
            # pathological case of deliveries arriving while the prod
            # loop is starved — votes never wait past one timer turn.
            self._arm_outbox_flush()
            return result
        network.process_incoming = filtering_incoming

        # ---- runtime ownership sanitizer (runtime/sanitizer.py): the
        # runtime twin of plenum-lint PT016/PT017 — region pins on
        # consensus-critical objects, shared by the ordering services'
        # 3PC-intake guard, the executor's commit/lane seams and the
        # pipeline's handoff tokens. The construction thread IS the
        # prod thread (nodes are built and serviced on one thread; the
        # pipelined path re-binds below with its own ident). Opt-in:
        # Config.SANITIZER_ENABLED / PLENUM_TPU_SANITIZE=1.
        from plenum_tpu.runtime.sanitizer import (
            CONSENSUS_PINS, OwnershipSanitizer, sanitizer_enabled)
        self.sanitizer = None
        if sanitizer_enabled(self.config):
            self.sanitizer = OwnershipSanitizer(
                name=name, tracer=self.tracer)
            self.sanitizer.bind_region("prod")
            for label in CONSENSUS_PINS:
                self.sanitizer.pin(label, "prod")
            for replica in self.replicas:
                replica.ordering.attach_sanitizer(self.sanitizer)
            self.executor.set_sanitizer(self.sanitizer)

        # ---- pipeline runtime (runtime/pipeline.py): wire parse +
        # ed25519 pre-screen move to a worker thread feeding the prod
        # thread through a bounded queue; execution fan-out shares the
        # same pool. The serial path above stays the validated
        # fallback; the prod thread keeps sole ownership of all
        # consensus state (bind_owner_thread makes that a hard
        # contract at the 3PC intake seams).
        self._pipeline = None
        self._prescreen_cache = None
        self._drain_scheduled = False
        self._serial_incoming = filtering_incoming
        if getattr(self.config, "PIPELINE_ENABLED", False):
            import threading
            from plenum_tpu.runtime.pipeline import (
                NodePipeline, PrescreenCache)
            from plenum_tpu.crypto.batch_verifier import create_verifier
            self._prescreen_cache = PrescreenCache()
            self._prescreen_verifier = create_verifier("cpu")
            # ONE verdict cache across both authenticators: client
            # intake warms it (warm-on-verify), the worker pre-screen
            # and the propagate gate skip triples it has seen — the
            # ~n relayed copies of a request cost one verification
            propagate_authnr.set_prescreen(self._prescreen_cache)
            self.authnr.set_prescreen(self._prescreen_cache)
            self._pipeline = NodePipeline(
                self._pipeline_deliver, config=self.config,
                telemetry=self.telemetry, tracer=self.tracer,
                name=name, sanitizer=self.sanitizer)
            self.executor.set_exec_map(self._pipeline.exec_map)
            prod_ident = threading.get_ident()
            for replica in self.replicas:
                replica.ordering.bind_owner_thread(prod_ident)
            # per-stage drain on view change: no parse job may
            # straddle a protocol epoch (catchup drains in
            # start_catchup the same way)
            self.replica.internal_bus.subscribe(
                ViewChangeStarted, lambda msg: self._drain_pipeline())

            def pipelined_incoming(msg, frm):
                # connection events keep their inline path (monitors
                # track peers whether queued work exists or not)
                if isinstance(msg, (network.Connected,
                                    network.Disconnected)):
                    return filtering_incoming(msg, frm)
                if isinstance(msg, FlatBatch):
                    payload = msg.payload
                    self._pipeline.submit(
                        lambda: self._pipeline_parse(payload, frm),
                        msg, frm)
                else:
                    self._pipeline.submit(None, msg, frm)
                # zero-delay drain: fires at THIS simulated instant,
                # after the delivery callback returns, so pipelined
                # processing happens at the same sim time — and in
                # the same order — the serial path would have
                # processed it (determinism by construction; the
                # wall-clock win is the worker parsing concurrently)
                if not self._drain_scheduled:
                    self._drain_scheduled = True
                    self.timer.schedule(0, self._drain_pipeline)
            network.process_incoming = pipelined_incoming
        self.mode_participating = True

        # ---- restart recovery from persisted stores
        self._recover_from_storage()

    # ========================================================== genesis

    def _load_genesis(self, txns: List[dict]):
        """Seed ledgers/state from genesis transactions (reference
        ledger/genesis_txn/ + upload_states)."""
        from plenum_tpu.common.txn_util import get_type
        for txn in txns:
            txn_type = get_type(txn)
            handler = self.write_manager.request_handlers.get(txn_type)
            if handler is None:
                continue
            ledger = handler.ledger
            ledger.add(dict(txn))
            handler.update_state(txn, None, None, is_committed=True)
            if handler.state is not None:
                handler.state.commit()

    # ================================================== pool membership

    def _on_validators_changed(self, new_validators: List[str]):
        """A committed NODE txn changed pool membership: re-derive
        quorums/f on every protocol instance, adjust the backup instance
        count, update primary selectors (future views only — the current
        primary never silently moves), reconnect the transport, and vote
        a view change if the current primary was demoted (reference
        pool_manager.py + adjustReplicas node.py:1260)."""
        from plenum_tpu.common.messages.internal_messages import (
            VoteForViewChange)
        for replica in self.replicas:
            replica.data.set_validators(new_validators)
            replica.selector.validators[:] = new_validators
        self._primary_selector.validators[:] = new_validators
        self.replicas.adjust_replicas(new_validators)
        self.propagator.update_quorums(self.replica.data.quorums)
        if self.bls_bft_replica is not None:
            self.bls_bft_replica.warm_pool_keys(new_validators)
        if self._on_membership_change is not None:
            self._on_membership_change(new_validators)
        if self.name not in new_validators:
            logger.info("%s demoted from the pool — stops participating",
                        self.name)
            self.mode_participating = False
            for replica in self.replicas:  # backups must stop voting too
                replica.data.node_mode_participating = False
            return
        if not self.mode_participating and not self.leecher.in_progress:
            # re-promoted: sync the missed window BEFORE voting again —
            # everything ordered while passive sits stashed/unapplied
            logger.info("%s re-promoted — catching up before rejoining",
                        self.name)
            self.start_catchup()
        primary = self.replica.data.primary_name
        if primary is not None and primary not in new_validators:
            logger.info("%s: primary %s demoted — voting view change",
                        self.name, primary)
            self.replica.internal_bus.send(
                VoteForViewChange(suspicion="PRIMARY_DEMOTED"))

    # ========================================================== recovery

    def _recover_from_storage(self):
        """Node restart from persisted stores (reference node restart:
        ledgers recoverTree on init, states re-derived from txn logs via
        ledgers_bootstrap.upload_states, seqNoDB reload node.py:698,
        3PC position from the audit ledger — SURVEY.md §5.4)."""
        from plenum_tpu.common.txn_util import get_payload_digest, get_type
        from plenum_tpu.state.trie import BLANK_ROOT
        expected_roots = self._audit_state_roots()
        for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID):
            ledger = self.db_manager.get_ledger(lid)
            state = self.db_manager.get_state(lid)
            if ledger.size == 0 or state is None:
                continue
            expected = expected_roots.get(lid)
            if state.committedHeadHash != BLANK_ROOT and (
                    expected is None
                    or state.committedHeadHash == expected):
                continue  # state store survived and matches the audit
            # state store lost, or STALE (crash between the ledger flush
            # and the state-root commit): replay the txn log from scratch
            logger.info("%s rebuilding state for ledger %d from %d txns",
                        self.name, lid, ledger.size)
            state.revertToHead(BLANK_ROOT)
            for _, txn in ledger.getAllTxn():
                handler = self.write_manager.request_handlers.get(
                    get_type(txn))
                if handler is not None and handler.ledger_id == lid:
                    handler.update_state(txn, None, None, is_committed=True)
            state.commit()
            if expected is not None and \
                    state.committedHeadHash != expected:
                logger.warning(
                    "%s ledger %d state root %s still differs from audit "
                    "record after rebuild", self.name, lid,
                    state.committedHeadHash_b58)
        # dedup index: backfill entries the ledgers have that the index
        # lacks — a crash between the (separate) ledger and index stores
        # can lose individual puts, not just the whole index. Fast path:
        # if each ledger's LAST txn is indexed, the tail is intact and
        # the O(ledger) scan is skipped.
        for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID):
            ledger = self.db_manager.get_ledger(lid)
            if ledger.size == 0:
                continue
            last_digest = get_payload_digest(ledger.get_last_txn())
            if last_digest:
                try:
                    self.seq_no_db.get(last_digest.encode())
                    continue
                except KeyError:
                    pass
            for seq, txn in ledger.getAllTxn():
                payload_digest = get_payload_digest(txn)
                if not payload_digest:
                    continue
                try:
                    self.seq_no_db.get(payload_digest.encode())
                except KeyError:
                    self.seq_no_db.put(
                        payload_digest.encode(),
                        "{}:{}".format(lid, seq).encode())
        # state_ts backfill: a crash between the state commit and the
        # ts-store put loses the final batch's (pp_time → root) entry —
        # restore it from the last audit txn, which records every
        # ledger's state root at that batch
        ts_store = self.db_manager.get_store("state_ts")
        audit = self.db_manager.get_ledger(AUDIT_LEDGER_ID)
        if ts_store is not None and audit.size > 0:
            from plenum_tpu.common.txn_util import get_txn_time
            from plenum_tpu.server.batch_handlers import AUDIT_TXN_STATE_ROOT
            last_audit = audit.get_last_txn()
            txn_time = get_txn_time(last_audit)
            roots = get_payload_data(last_audit).get(
                AUDIT_TXN_STATE_ROOT) or {}
            if txn_time is not None:
                for lid_str, root_b58 in roots.items():
                    lid = int(lid_str)
                    if ts_store.get(txn_time, lid) is None:
                        ledger = self.db_manager.get_ledger(lid)
                        ts_store.set(txn_time, ledger.strToHash(root_b58),
                                     lid)
        self._adopt_3pc_from_audit()
        if audit.size > 0:
            # a non-empty audit ledger at construction == restart from
            # persisted state; observers may want to know (reference
            # notifier restart/upgrade-complete events)
            self.notifier.send_cluster_restart(
                "Resumed at audit seq %d." % audit.size)
        # backup primaries resume their persisted pp_seq_no (master
        # recovers via catchup; see last_sent_pp_store.try_restore)
        self.last_sent_pp_store.try_restore(self)
        # a node with committed history must re-sync with the pool before
        # voting again: its persisted view is each batch's ORIGINAL view,
        # which can lag the pool's current view (catchup gathers f+1 peer
        # evidence via pool_view_estimate). Fresh-genesis nodes (empty
        # audit) participate immediately.
        if self.db_manager.get_ledger(AUDIT_LEDGER_ID).size > 0:
            self.start_catchup()

    def _primaries_for_batch(self, original_view_no: int) -> List[str]:
        """Primaries recorded in a batch's audit txn. Must be stable for
        the WHOLE view regardless of later membership changes (the
        reference records primaries at view start and back-references
        after, audit_batch_handler._fill_primaries): if the previous
        audit txn belongs to the same original view, reuse ITS resolved
        primaries; only the first batch of a view derives them from the
        live selector."""
        handler = self._audit_handler()
        if handler is not None:
            last_seq = handler.ledger.uncommitted_size
            if last_seq:
                last = handler.ledger.get_by_seq_no_uncommitted(last_seq)
                if last is not None and \
                        get_payload_data(last).get("viewNo") == \
                        original_view_no:
                    prev = handler.primaries_at(last_seq)
                    if prev:
                        return list(prev)
        return [self._primary_selector.select_master_primary(
            original_view_no)]

    def _audit_handler(self):
        from plenum_tpu.server.batch_handlers import AuditBatchHandler
        for chain in self.write_manager.batch_handlers.values():
            for h in chain:
                if isinstance(h, AuditBatchHandler):
                    return h
        return None

    def _audit_state_roots(self) -> Dict[int, bytes]:
        """ledger_id → expected committed state root from the last audit
        txn (every audit txn records all current state roots)."""
        audit = self.db_manager.get_ledger(AUDIT_LEDGER_ID)
        last = audit.get_last_txn()
        if last is None:
            return {}
        from plenum_tpu.ledger.ledger import Ledger
        roots = {}
        for lid_str, root_b58 in (
                get_payload_data(last).get("stateRoot") or {}).items():
            try:
                roots[int(lid_str)] = Ledger.strToHash(root_b58)
            except Exception:
                continue
        return roots

    def _adopt_3pc_from_audit(self, pool_view: Optional[int] = None):
        """Fast-forward the replica to the audit ledger's last recorded
        3PC position (floor: the audit view is the batch's ORIGINAL view;
        `pool_view` — peer evidence from catchup — can raise it)."""
        audit = self.db_manager.get_ledger(AUDIT_LEDGER_ID)
        last_audit = audit.get_last_txn()
        view_no, pp_seq_no = 0, 0
        if last_audit is not None:
            data = get_payload_data(last_audit)
            view_no = data.get("viewNo", 0)
            pp_seq_no = data.get("ppSeqNo", 0)
        # a batch ORDERED in the view we're still waiting on proves its
        # NEW_VIEW completed pool-wide while we weren't looking (likely
        # disconnected) — absorb the pending view change from this
        # evidence, or the node wedges: NEW_VIEW is never retransmitted
        # and MessageReq is disabled mid view change (audit viewNo is
        # the batch's ORIGINAL view, so re-ordered old-view batches
        # never count as evidence — only genuinely new ones)
        if last_audit is not None \
                and self.replica.data.waiting_for_new_view:
            vc_service = getattr(self.replica, "view_changer", None)
            if vc_service is not None:
                vc_service.absorb_view_from_catchup(view_no)
        if pool_view is not None:
            view_no = max(view_no, pool_view)
        current = self.replica.data.last_ordered_3pc
        if (view_no, pp_seq_no) <= current:
            return
        pp_seq_no = max(pp_seq_no, current[1])
        view_was = self.replica.data.view_no
        self.replica.data.last_ordered_3pc = (view_no, pp_seq_no)
        self.replica.data.view_no = view_no
        # absorb didn't fire (no batch ordered at the pending view yet)
        # but pool evidence re-targeted a still-pending view change to
        # a HIGHER view: the running NEW_VIEW timer's view guard now
        # never matches, so re-arm it for the adopted view — the node
        # keeps escalating/voting instead of wedging silently
        if view_no > view_was \
                and self.replica.data.waiting_for_new_view:
            vc_service = getattr(self.replica, "view_changer", None)
            if vc_service is not None:
                vc_service.rearm_new_view_timeout()
        self.replica.ordering.lastPrePrepareSeqNo = pp_seq_no
        self.replica.ordering._last_applied_seq = pp_seq_no
        self.replica.checkpointer.caught_up_till_3pc((view_no, pp_seq_no))
        # primary: prefer the audit ledger's own record (stable against
        # mid-view membership changes); the live selector only decides
        # views newer than the last audited batch
        primary = None
        if last_audit is not None and \
                get_payload_data(last_audit).get("viewNo") == view_no:
            handler = self._audit_handler()
            recorded = handler.primaries_at(audit.size) if handler else None
            if recorded:
                primary = recorded[0]
        self.replica.data.primary_name = primary or \
            self._primary_selector.select_master_primary(view_no)

    # ===================================================== client intake

    def process_client_request(self, msg: dict, client_id: str):
        """Entry for one client REQUEST (reference processRequest :2000)."""
        with self.metrics.measure_time(MetricsName.REQUEST_INTAKE_TIME):
            self._process_client_request(msg, client_id)

    def _process_client_request(self, msg: dict, client_id: str):
        try:
            self._validator.validate(msg)
            request = Request.from_dict(msg)
        except InvalidClientMessageException as e:
            self._reply_to_client(client_id, RequestNack(
                identifier=msg.get("identifier") or "unknown",
                reqId=msg.get("reqId") or 0, reason=str(e)))
            return
        if self.read_manager.is_valid_type(request.txn_type):
            self._process_read(request, client_id)
            return
        if self.action_manager.is_valid_type(request.txn_type):
            self._process_action(request, client_id)
            return
        self._process_write(request, client_id)

    def process_client_batch(self, msgs: List[Tuple[dict, str]]):
        """Batched intake: ONE device dispatch authenticates every pending
        request (the north-star path)."""
        pending = self.dispatch_client_batch(msgs)
        self.conclude_client_batch(pending)

    def dispatch_client_batch(self, msgs: List[Tuple[dict, str]]):
        """Phase 1 of batched intake (non-blocking): validate schemas,
        serve reads, enqueue ONE async device dispatch for every write
        signature. The caller overlaps other work (other nodes\' batches,
        consensus ticks) before conclude_client_batch harvests — this
        hides the device round-trip latency entirely (SURVEY.md §7)."""
        with self.metrics.measure_time(MetricsName.DEVICE_DISPATCH_TIME), \
                self.tracer.span("auth_dispatch", CAT_DEVICE,
                                 n=len(msgs)) as _sp:
            pending = self._dispatch_client_batch(msgs)
            if pending is not None:
                _sp.add(dispatched=len(pending[0]))
            return pending

    def _dispatch_client_batch(self, msgs: List[Tuple[dict, str]]):
        from plenum_tpu.common.constants import CURRENT_PROTOCOL_VERSION
        intake = _fp.request_intake if _fp is not None else None
        parsed = []
        reads = []
        for msg, client_id in msgs:
            try:
                # C fast path: validation + both digests + signing bytes
                # in one crossing; None falls back to the Python chain
                # (which also produces the exact rejection text)
                pre = None
                if intake is not None and type(msg) is dict:
                    pre = intake(msg, CURRENT_PROTOCOL_VERSION)
                if pre is None:
                    self._validator.validate(msg)
                    request = Request.from_dict(msg)
                else:
                    request = Request.from_dict(msg)
                    request._digest, request._payload_digest, \
                        request._signing_ser = pre
            except InvalidClientMessageException as e:
                self._reply_to_client(client_id, RequestNack(
                    identifier=msg.get("identifier") or "unknown",
                    reqId=msg.get("reqId") or 0, reason=str(e)))
                continue
            if self.read_manager.is_valid_type(request.txn_type):
                # defer: the whole intake's reads serve as ONE batch
                # (shared state-engine walks + per-root BLS lookups)
                reads.append((request, client_id))
                continue
            if self.action_manager.is_valid_type(request.txn_type):
                self._process_action(request, client_id)
                continue
            parsed.append((request, client_id))
        self._process_read_batch(reads)
        if not parsed:
            return None
        self.metrics.add_event(MetricsName.CLIENT_AUTH_BATCH_SIZE,
                               len(parsed))
        self.tracer.counter("auth_batch_size", len(parsed))
        handle = self.authnr.dispatch_batch([r for r, _ in parsed])
        return (parsed, handle)

    def client_batch_ready(self, pending) -> bool:
        """True when conclude_client_batch will not block (device/daemon
        result landed)."""
        if pending is None:
            return True
        _, handle = pending
        return self.authnr.batch_ready(handle)

    def conclude_client_batch(self, pending):
        """Phase 2: harvest device results, ack/nack, propagate."""
        if pending is None:
            return
        parsed, handle = pending
        with self.metrics.measure_time(MetricsName.CLIENT_AUTH_TIME), \
                self.tracer.span("auth_conclude", CAT_DEVICE,
                                 n=len(parsed)):
            results = self.authnr.conclude_batch(handle)
        for (request, client_id), idrs in zip(parsed, results):
            if idrs is None:
                self._reply_to_client(client_id, RequestNack(
                    identifier=request.identifier or "unknown",
                    reqId=request.reqId or 0,
                    reason="signature verification failed"))
                continue
            self._accept_write(request, client_id)
        # ship the whole intake batch's propagates as one wire message
        self.propagator.flush()

    # ------------------------------------------------- gateway intake

    def process_gateway_envelope(self, data, frm: str):
        """Client-tier FLAT_WIRE intake: one PROPAGATE-only envelope
        from a gateway becomes one batched client intake. The gateway's
        pre-screen is only a filter — every request re-authenticates
        here through the same ``process_client_batch`` path direct
        client traffic takes, so the ledger/state roots produced from a
        gateway-fed stream are byte-identical to feeding the same
        admitted requests directly."""
        msgs = self.unpack_gateway_batch(data, frm)
        if msgs:
            self.process_client_batch(msgs)

    def unpack_gateway_batch(self, data,
                             frm: str) -> List[Tuple[dict, str]]:
        """Parse one gateway→node envelope into [(request dict, client
        id)]. Structural violations (bad magic/version, truncation,
        over-length, non-PROPAGATE sections — a gateway never forwards
        3PC traffic) raise a per-sender suspicion and drop the envelope
        whole; a bad request ENTRY costs only itself."""
        hub = get_seam_hub()
        try:
            env = flat_wire.parse_envelope(
                data, max_bytes=self.config.MSG_LEN_LIMIT)
        except flat_wire.FlatWireError as e:
            hub.count(TM.WIRE_MALFORMED, 1)
            logger.warning("%s: malformed gateway envelope from %s: %s",
                           self.name, frm, e)
            self.blacklister.report_suspicion(
                frm, Suspicions.WIRE_MALFORMED, str(e),
                auto_blacklist=self.config.BLACKLIST_ON_SUSPICION)
            return []
        hub.count(TM.WIRE_BYTES_RECV, env.nbytes)
        msgs: List[Tuple[dict, str]] = []
        for sec in env.sections:
            if sec.kind != flat_wire.KIND_PROPAGATE:
                hub.count(TM.WIRE_MALFORMED, 1)
                logger.warning(
                    "%s: non-PROPAGATE section %d in gateway envelope "
                    "from %s", self.name, sec.kind, frm)
                self.blacklister.report_suspicion(
                    frm, Suspicions.WIRE_MALFORMED,
                    "gateway section kind %d" % sec.kind,
                    auto_blacklist=self.config.BLACKLIST_ON_SUSPICION)
                return []
            for i in range(sec.n):
                try:
                    req = sec.request(i)
                except Exception:
                    logger.warning("%s: bad request entry in gateway "
                                   "envelope from %s — dropped",
                                   self.name, frm)
                    continue
                msgs.append((req, sec.client(i) or frm))
        return msgs

    def _process_write(self, request: Request, client_id: str):
        try:
            self.req_authenticator.authenticate(request)
        except Exception as e:
            self._reply_to_client(client_id, RequestNack(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=str(e)))
            return
        self._accept_write(request, client_id)

    def _accept_write(self, request: Request, client_id: str):
        try:
            self.write_manager.static_validation(request)
        except InvalidClientMessageException as e:
            self._reply_to_client(client_id, RequestNack(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=str(e)))
            return
        # dedup: already committed? (must precede the plugin veto —
        # resubmission of a committed request returns its Reply even if
        # a later-installed plugin would now reject the operation)
        existing = self._committed_reply(request)
        if existing is not None:
            self._reply_to_client(client_id, existing)
            return
        # VERIFICATION plugins veto operations by raising (reference
        # plugin_loader.py:41 — Node calls each plugin's verify(msg) on
        # client requests)
        for plugin in self._verification_plugins:
            try:
                plugin.verify(request.operation)
            except Exception as e:
                self._reply_to_client(client_id, RequestNack(
                    identifier=request.identifier or "unknown",
                    reqId=request.reqId or 0,
                    reason="plugin rejected: %s" % e))
                return
        self._request_spike_accum += 1
        key = request.key
        # lifecycle root: everything downstream (propagate quorum, 3PC,
        # reply) correlates back to this digest on the merged timeline
        self.tracer.instant("request_accepted", CAT_INTAKE, key=key)
        if self.telemetry.enabled:
            # intake→reply latency start mark; a full map (pool deeply
            # backlogged) degrades to counting the drop, never growing
            if len(self._tm_intake_ts) < getattr(
                    self.config, "TELEMETRY_PENDING_MAX", 1 << 17):
                self._tm_intake_ts[key] = self.telemetry.clock()
            else:
                self.telemetry.count(TM.E2E_DROPPED)
        self._req_clients[key] = client_id
        if self._clients_attached:
            # building the Ack (schema-validated message object) only
            # makes sense when there is a transport to carry it
            self._reply_to_client(client_id, RequestAck(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0))
        self.monitor.request_received(key)
        self.propagator.propagate(request, client_id)

    def _sample_spikes(self):
        """One periodic sample per stream: client-request intake count
        (reference node.py:2561 sendNodeRequestSpike) and master EMA
        throughput (reference monitor.py:645 sendClusterThroughputSpike);
        STATS_CONSUMER plugins get the same snapshot."""
        from plenum_tpu.server.plugins import (
            TOPIC_CLUSTER_THROUGHPUT_SPIKE, TOPIC_NODE_REQUEST_SPIKE)
        reqs = self._request_spike_accum
        self._request_spike_accum = 0
        if self.mode_participating:
            self.notifier.send_spike_check(TOPIC_NODE_REQUEST_SPIKE, reqs)
            thr = self.monitor.instance_throughput(0)
            if thr is not None:
                self.notifier.send_spike_check(
                    TOPIC_CLUSTER_THROUGHPUT_SPIKE, thr)
        if self._stats_plugins:
            stats = {"node": self.name,
                     "requests_in_window": reqs,
                     "total_ordered": self.monitor.total_ordered,
                     "avg_latency": self.monitor.avg_latency(),
                     "master_throughput":
                         self.monitor.instance_throughput(0)}
            for plugin in self._stats_plugins:
                try:
                    plugin.consume_stats(stats)
                except Exception:
                    logger.error("stats plugin %r failed", plugin,
                                 exc_info=True)

    def _process_action(self, request: Request, client_id: str):
        """Authenticated action: validated + executed locally, no
        consensus round (reference node.py:2085 process_action). Rides
        the SAME authenticator registry as writes — actions are the
        privileged requests that most need every registered policy."""
        try:
            self.action_manager.static_validation(request)
            self.req_authenticator.authenticate(request)
        except Exception as e:
            self._reply_to_client(client_id, RequestNack(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=str(e)))
            return
        self._reply_to_client(client_id, RequestAck(
            identifier=request.identifier, reqId=request.reqId))
        try:
            self.action_manager.dynamic_validation(request)
            result = self.action_manager.process_action(request)
            self._reply_to_client(client_id, Reply(result=result))
        except Exception as e:
            self._reply_to_client(client_id, Reject(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=str(e)))

    def _process_read_batch(self, reads):
        """Serve one intake's reads as a single batch: GET_NYMs reading
        the same root share ONE batched state-engine walk for values
        and proofs (ReadRequestManager.get_results_batch). Per-request
        failures nack that request only; a manager-level failure falls
        back to the per-request path, so batching can never answer
        worse than serving one at a time."""
        if not reads:
            return
        if len(reads) == 1:
            self._process_read(*reads[0])
            return
        with self.tracer.span("read_batch", CAT_INTAKE, n=len(reads)):
            try:
                results = self.read_manager.get_results_batch(
                    [request for request, _ in reads])
            except Exception:
                logger.exception("%s batched read serving failed; "
                                 "serving one at a time", self.name)
                for request, client_id in reads:
                    self._process_read(request, client_id)
                return
        for (request, client_id), result in zip(reads, results):
            if isinstance(result, InvalidClientMessageException):
                self._reply_to_client(client_id, RequestNack(
                    identifier=request.identifier or "unknown",
                    reqId=request.reqId or 0, reason=str(result)))
            elif isinstance(result, Exception):
                logger.error("%s failed processing read %s: %r",
                             self.name, request, result)
                self._reply_to_client(client_id, RequestNack(
                    identifier=request.identifier or "unknown",
                    reqId=request.reqId or 0, reason="internal error"))
            else:
                self._reply_to_client(client_id, Reply(result=result))

    def _process_read(self, request: Request, client_id: str):
        try:
            result = self.read_manager.get_result(request)
            self._reply_to_client(client_id, Reply(result=result))
        except InvalidClientMessageException as e:
            self._reply_to_client(client_id, RequestNack(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=str(e)))
        except Exception:  # a read must never crash the intake loop
            logger.exception("%s failed processing read %s", self.name,
                             request)
            self._reply_to_client(client_id, RequestNack(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason="internal error"))

    # ================================================ propagation → 3PC

    def _forward_finalised(self, request: Request):
        # POOL_LEDGER_ID is 0 — `or` would misroute NODE txns to domain
        lid = self.write_manager.type_to_ledger_id(request.txn_type)
        if lid is None:
            lid = DOMAIN_LEDGER_ID
        self._tm_propagate_done(request.key)
        self.replicas.submit_request(request.key, lid)

    def _forward_finalised_batch(self, requests: List[Request]):
        """A whole propagate batch finalised at once: digests stay one
        contiguous column per ledger into every instance's proposal
        queue (one stash-replay per instance per batch, not per
        request)."""
        by_ledger: Dict[int, List[str]] = {}
        type_to_lid = self.write_manager.type_to_ledger_id
        for request in requests:
            lid = type_to_lid(request.txn_type)
            if lid is None:
                lid = DOMAIN_LEDGER_ID
            self._tm_propagate_done(request.key)
            by_ledger.setdefault(lid, []).append(request.key)
        for lid, digests in by_ledger.items():
            self.replicas.submit_requests(digests, lid)

    def _tm_propagate_done(self, key: str) -> None:
        """Propagate-quorum wait histogram: intake accept → forwarded to
        the ordering queues (quorum reached). Requests learned only via
        gossip have no intake mark here — their latency is owned by the
        node that accepted them from the client."""
        t0 = self._tm_intake_ts.get(key)
        if t0 is not None:
            self.telemetry.observe(TM.STAGE_PROPAGATE_MS,
                                   (self.telemetry.clock() - t0) * 1e3)

    def _arm_outbox_flush(self):
        """Arm the deferred vote flush when an inbound delivery left
        provoked votes in the 3PC outbox — shared by the serial
        delivery path and the pipeline drain."""
        if self._outbox_3pc is not None and len(self._outbox_3pc) \
                and not self._outbox_flush_armed:
            self._outbox_flush_armed = True
            self.timer.schedule(
                getattr(self.config, "THREE_PC_FLUSH_WINDOW", 0.002),
                self._deferred_outbox_flush)

    def _deferred_outbox_flush(self):
        """Timer-armed flush covering votes provoked by deliveries:
        armed on the FIRST provoked vote and fired one
        THREE_PC_FLUSH_WINDOW later, so a burst of deliveries jittered
        across a few ms (per-message wire latency draws) accumulates
        into ONE envelope of everything it provoked — without the
        window every provoked vote shipped alone, because each
        instance's PP arrives from a different primary at a different
        instant. A few ms of extra vote latency is invisible next to
        consensus timeouts, and the prod-tick flush in service() still
        bounds the wait when the timer is starved."""
        self._outbox_flush_armed = False
        if self._outbox_3pc is not None:
            self._outbox_3pc.flush()

    def _process_three_pc_batch(self, msg: ThreePCBatch, frm: str):
        """Inbound coalesced 3PC envelope: reconstruct wire entries,
        split by protocol instance, and feed each instance's columnar
        intake — PRE-PREPAREs first, then PREPAREs, then COMMITs (a
        sender's envelope is FIFO, and no sender emits a vote before
        its own earlier-phase vote for the same key, so phase-major
        processing preserves per-sender causality)."""
        ctx = getattr(msg, "traceCtx", None)
        if ctx is not None:
            self._note_wire_stamp(
                flat_wire.TraceStamp.from_wire(ctx), frm, CAT_3PC)
        groups: Dict[int, Tuple[list, list, list]] = {}
        # the typed path's receive-side deserialization cost — one
        # factory reconstruction per inner vote — is the `parse` stage
        # the flat codec's single-parse replaces; span it so the A/B
        # reads off scripts/trace_budget instead of being inferred
        with self.tracer.span("wire_parse", CAT_3PC,
                              n=len(msg.messages)):
            for entry in msg.messages:
                if isinstance(entry, dict):
                    try:
                        entry = node_message_factory.get_instance(**entry)
                    except Exception as e:
                        logger.warning(
                            "%s: bad entry in THREE_PC_BATCH from %s: %s",
                            self.name, frm, e)
                        continue
                if isinstance(entry, PrePrepare):
                    idx = 0
                elif isinstance(entry, Prepare):
                    idx = 1
                elif isinstance(entry, Commit):
                    idx = 2
                else:
                    logger.warning(
                        "%s: non-3PC entry %s in THREE_PC_BATCH from %s "
                        "— dropped", self.name, type(entry).__name__, frm)
                    continue
                inst_id = entry.instId
                group = groups.get(inst_id)
                if group is None:
                    group = groups[inst_id] = ([], [], [])
                group[idx].append(entry)
        for inst_id, (pps, prepares, commits) in groups.items():
            replica = self.replicas.get(inst_id)
            if replica is None:
                continue   # fewer instances here than at the sender
            ordering = replica.ordering
            if pps:
                ordering.process_preprepare_batch(pps, frm)
            if prepares:
                ordering.process_prepare_batch(prepares, frm)
            if commits:
                ordering.process_commit_batch(commits, frm)

    def _process_flat_batch(self, msg: FlatBatch, frm: str):
        """Inbound flat zero-copy envelope: ONE parse turns the payload
        bytes into numpy column views (no per-message deserialization,
        no intermediate message objects), split per protocol instance
        and fed phase-major into the columnar ``process_*_columns``
        intake — PRE-PREPAREs first (materialized from their
        length-prefixed section: they carry ragged reqIdr and must run
        the full stash/verdict machinery), then PREPARE columns, then
        COMMIT columns. A structurally invalid envelope raises a
        per-sender suspicion and is dropped whole — it can never crash
        the prod loop; a bad ENTRY costs only itself, like a bad entry
        in a typed THREE_PC_BATCH."""
        payload = msg.payload
        try:
            with self.tracer.span(
                    "wire_parse", CAT_3PC,
                    n=len(payload) if isinstance(
                        payload, (bytes, bytearray)) else 0):
                env = flat_wire.parse_envelope(payload)
        except flat_wire.FlatWireError as e:
            self._flat_wire_suspicion(frm, e)
            return
        self._note_flat_stamp(env, frm)
        self._dispatch_parsed_flat(env, frm)

    def _flat_wire_suspicion(self, frm: str, e: Exception) -> None:
        """A structurally invalid envelope: sender-attributable
        suspicion, envelope dropped whole — the wire can never crash
        the prod loop. Shared by the serial parse path and the
        pipeline drain (a worker parse failure is delivered here, on
        the prod thread, in arrival order — same verdict, same
        instant the serial path would have raised it)."""
        get_seam_hub().count(TM.WIRE_MALFORMED, 1)
        logger.warning("%s: malformed FLAT_WIRE envelope from %s: %s",
                       self.name, frm, e)
        self.blacklister.report_suspicion(
            frm, Suspicions.WIRE_MALFORMED, str(e),
            auto_blacklist=self.config.BLACKLIST_ON_SUSPICION)

    def _note_flat_stamp(self, env, frm: str) -> None:
        """The envelope's receive-side journey anchor. On the
        pipelined path this runs on the PARSE WORKER (the tracer's
        ring is lock-protected), so the wire_recv instant lands at
        true arrival time rather than drain time — journeys stay
        complete and honest about when bytes hit the node."""
        if env.stamp is not None:
            self._note_wire_stamp(
                env.stamp, frm,
                CAT_PROPAGATE if all(
                    s.kind == flat_wire.KIND_PROPAGATE
                    for s in env.sections) else CAT_3PC)

    def _dispatch_parsed_flat(self, env, frm: str) -> None:
        """Feed one parsed envelope into the columnar intakes —
        ALWAYS on the prod thread (serial path inline; pipelined path
        from the drain), because everything below this line touches
        consensus state."""
        get_seam_hub().count(TM.WIRE_BYTES_RECV, env.nbytes)
        # inst -> (pps, prepare column slices, commit column slices);
        # phase-major per instance preserves per-sender causality (a
        # sender's envelope is FIFO and no sender votes ahead of its
        # own earlier phase for the same key)
        groups: Dict[int, Tuple[list, list, list]] = {}

        def group(inst_id: int) -> Tuple[list, list, list]:
            g = groups.get(inst_id)
            if g is None:
                g = groups[inst_id] = ([], [], [])
            return g

        propagate_secs = []
        for sec in env.sections:
            if sec.kind == flat_wire.KIND_PREPREPARE:
                for i in range(sec.n):
                    pp = sec.materialize(i)
                    if pp is None:
                        logger.warning(
                            "%s: bad PREPREPARE entry in FLAT_WIRE "
                            "from %s — dropped", self.name, frm)
                        continue
                    group(pp.instId)[0].append(pp)
            elif sec.kind == flat_wire.KIND_PREPARE:
                self._split_columns_by_inst(sec, group, 1)
            elif sec.kind == flat_wire.KIND_COMMIT:
                self._split_columns_by_inst(sec, group, 2)
            elif sec.kind == flat_wire.KIND_PROPAGATE:
                propagate_secs.append(sec)
        for inst_id, (pps, prep_cols, commit_cols) in groups.items():
            replica = self.replicas.get(inst_id)
            if replica is None:
                continue   # fewer instances here than at the sender
            ordering = replica.ordering
            if pps:
                ordering.process_preprepare_batch(pps, frm)
            for cols in prep_cols:
                ordering.process_prepare_columns(cols, frm)
            for cols in commit_cols:
                ordering.process_commit_columns(cols, frm)
        for sec in propagate_secs:
            self.propagator.process_propagate_columns(sec, frm)

    def _note_wire_stamp(self, stamp, frm: str, cat: str) -> None:
        """Advisory receive-side journey anchor: one ``wire_recv``
        instant joining this envelope to its sender's ``wire_send`` by
        (origin, flush seq). The stamp is observability context only —
        a missing/corrupt stamp decodes to None upstream and message
        handling proceeds identically (plenum-lint PT015 pins that no
        consensus path can reach stamp content)."""
        if stamp is None or not self.tracer.enabled:
            return
        _, recv_wall = self.tracer.clock_pair()
        self.tracer.instant(
            "wire_recv", cat,
            key="%s:%d" % (stamp.origin, stamp.seq),
            origin=stamp.origin, seq=stamp.seq, frm=frm,
            sent_perf=stamp.perf_ts, sent_wall=stamp.wall_ts,
            recv_wall=recv_wall)

    @staticmethod
    def _split_columns_by_inst(sec, group, slot: int) -> None:
        """Route one vote-column section to every instance present in
        its instId column. The section is handed over WHOLE — each
        instance's columnar precheck discards the other instances'
        rows in the same scalar pass it already runs — because at
        wire-typical sizes (a few votes per instance per envelope)
        per-instance fancy-index slicing costs more than the repeated
        C-level compares it would save (the digest_match_mask
        measurement, again)."""
        seen = dict.fromkeys(sec.inst.tolist())
        for inst in seen:
            group(inst)[slot].append(sec)

    # ================================================= pipeline runtime

    def _drain_pipeline(self):
        """Deliver every queued pipeline job on the prod thread.
        Timer-armed at submission with ZERO delay, so the drain fires
        at the same simulated instant the serial path would have
        processed the delivery — byte-equal roots by construction —
        while the parse worker runs ahead of the prod thread inside
        each same-instant burst (all peers' envelopes from one flush
        sweep land together; parse of job i+1 overlaps dispatch of
        job i). Also called from service(), start_catchup and
        ViewChangeStarted so no job straddles a protocol epoch."""
        self._drain_scheduled = False
        if self._pipeline is not None:
            self._pipeline.drain()

    def _pipeline_parse(self, payload, frm: str):
        """WORKER-THREAD stage: payload bytes → ParsedEnvelope
        (immutable numpy views over the immutable buffer), the
        receive-instant journey anchor, and the advisory ed25519
        pre-screen. Touches NO consensus state. A FlatWireError
        propagates to the drain as the job's error — the suspicion is
        raised on the prod thread, in arrival order."""
        with self.tracer.span(
                "wire_parse", CAT_3PC,
                n=len(payload) if isinstance(
                    payload, (bytes, bytearray)) else 0):
            env = flat_wire.parse_envelope(payload)
        self._note_flat_stamp(env, frm)
        self._prescreen_propagates(env)
        return env

    def _prescreen_propagates(self, env) -> None:
        """WORKER-THREAD stage: verify every screenable PROPAGATE
        signature against its identifier-DERIVED (cryptonym) verkey
        and warm the positive-verdict cache, so the prod thread's
        authenticate_propagated skips the scalar verify on the hit
        path. Domain state is consensus state the worker must not
        read, so a request whose verkey lives only in domain state
        simply misses the cache and verifies on the prod thread
        exactly as before — filter, not authority, the gateway's
        argument. OpenSSL releases the GIL during the verify, so
        this runs truly concurrent with prod-side dispatch."""
        cache = self._prescreen_cache
        if cache is None:
            return
        items = []
        for sec in env.sections:
            if sec.kind != flat_wire.KIND_PROPAGATE:
                continue
            for i in range(sec.n):
                try:
                    req = sec.request(i)
                except Exception:
                    continue   # a bad entry costs only itself
                item = self._prescreen_item(req)
                # the pool relays every request ~n times (one PROPAGATE
                # per peer) and client intake verified it once already:
                # triples the cache has seen — from the authenticator's
                # warm-on-verify or an earlier copy — cost a dict probe
                # here, not a verify
                if item is not None and not cache.check(item):
                    items.append(item)
        if not items:
            return
        t0 = time.perf_counter()
        try:
            results = self._prescreen_verifier.verify_batch(items)
        except (ValueError, TypeError, RuntimeError) as e:
            # advisory: a broken screen = all-miss, never an outcome
            logger.debug("%s: pre-screen verify failed: %s",
                         self.name, e)
            return
        for item, ok in zip(items, results):
            if ok:
                cache.add(*item)
        self.telemetry.observe(
            TM.PIPELINE_PRESCREEN_MS,
            (time.perf_counter() - t0) * 1e3)

    @staticmethod
    def _prescreen_item(msg) -> Optional[tuple]:
        """(signing bytes, sig64, vk32) for a single-signature request
        dict using only sender-supplied material (the gateway's
        _verify_item shape), or None when unscreenable."""
        if not isinstance(msg, dict):
            return None
        sig = msg.get("signature")
        idr = msg.get("identifier")
        if not isinstance(sig, str) or not isinstance(idr, str) \
                or msg.get("signatures"):
            return None
        from plenum_tpu.common.serializers.base58 import b58decode
        from plenum_tpu.common.serializers.serialization import (
            serialize_msg_for_signing)
        from plenum_tpu.crypto.signer import verkey_from_identifier
        try:
            sig_raw = b58decode(sig)
            vk = verkey_from_identifier(idr, None)
            payload = {k: v for k, v in msg.items()
                       if k not in ("signature", "signatures")}
            ser = serialize_msg_for_signing(payload)
        except (ValueError, TypeError, KeyError):
            return None         # unscreenable shape: full verify later
        if len(sig_raw) != 64 or len(vk) != 32:
            return None
        return (ser, sig_raw, vk)

    def _pipeline_deliver(self, job) -> None:
        """PROD-THREAD delivery of one pipeline job, in arrival
        order. Blacklist verdicts, suspicions and every consensus
        side effect happen here — the worker only turned bytes into
        views. Non-FlatBatch jobs ride the serial path whole."""
        msg, frm = job.msg, job.frm
        if not isinstance(msg, FlatBatch):
            self._serial_incoming(msg, frm)
            return
        if self.blacklister.is_blacklisted(frm):
            return
        if job.error is not None:
            if isinstance(job.error, flat_wire.FlatWireError):
                self._flat_wire_suspicion(frm, job.error)
                return
            raise job.error
        self._dispatch_parsed_flat(job.result, frm)
        self._arm_outbox_flush()

    def _get_finalised_request(self, digest: str) -> Optional[Request]:
        state = self.propagator.requests.get(digest)
        return state.request if state else None

    # ===================================================== commit hooks

    def _on_backup_ordered(self, ordered: Ordered):
        """Backup instances never execute; they only feed the monitor's
        master-vs-backup throughput + latency comparisons (RBFT)."""
        self.metrics.add_event(MetricsName.BACKUP_ORDERED, 1)
        self.monitor.requests_ordered_bulk(
            [(d, None) for d in ordered.valid_reqIdr], ordered.instId)

    def _on_batch_committed(self, ordered: Ordered, committed_txns):
        """Send Replies with audit paths; update dedup index; free reqs."""
        with self.metrics.measure_time(MetricsName.REPLY_TIME), \
                self.telemetry.timer(TM.STAGE_REPLY_MS), \
                self.tracer.span(
                    "reply", CAT_REPLY,
                    key="%d:%d" % (ordered.viewNo, ordered.ppSeqNo),
                    txns=len(committed_txns or [])):
            self._on_batch_committed_inner(ordered, committed_txns)

    def _on_batch_committed_inner(self, ordered: Ordered, committed_txns):
        self.metrics.add_event(MetricsName.ORDERED_BATCH_COMMITTED,
                               len(committed_txns or []))
        if committed_txns:
            self.telemetry.count(TM.ORDERED_REQUESTS, len(committed_txns))
        self.observable.batch_committed(ordered.ledgerId,
                                        committed_txns or [])
        ledger = self.db_manager.get_ledger(ordered.ledgerId)
        # locals hoisted out of the per-txn loop: this runs once per
        # ordered request on every node
        from plenum_tpu.common.constants import (
            TXN_METADATA, TXN_METADATA_SEQ_NO, TXN_PAYLOAD,
            TXN_PAYLOAD_METADATA, TXN_PAYLOAD_METADATA_DIGEST,
            TXN_PAYLOAD_METADATA_FROM, TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST)
        seq_no_put = self.seq_no_db.put
        req_clients_pop = self._req_clients.pop
        rejected_pop = self._rejected_digests.pop
        free_request = self.propagator.requests.free
        tm_enabled = self.telemetry.enabled
        tm_intake_pop = self._tm_intake_ts.pop
        tm_observe = self.telemetry.observe
        tm_now = self.telemetry.clock() if tm_enabled else 0.0
        inst_id = ordered.instId
        lid_prefix = "%d:" % ordered.ledgerId
        reply_work = []       # (client_id, txn, seq_no) pending proofs
        ordered_pairs = []    # (digest, author) for ONE monitor call
        for txn in committed_txns or []:
            md = txn.get(TXN_PAYLOAD, {}).get(TXN_PAYLOAD_METADATA, {})
            seq_no = txn.get(TXN_METADATA, {}).get(TXN_METADATA_SEQ_NO)
            payload_digest = md.get(TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST)
            if payload_digest:
                seq_no_put(payload_digest.encode(),
                           (lid_prefix + str(seq_no)).encode())
            digest = md.get(TXN_PAYLOAD_METADATA_DIGEST)
            if digest:
                ordered_pairs.append(
                    (digest, md.get(TXN_PAYLOAD_METADATA_FROM)))
                rejected_pop(digest, None)
                if tm_enabled:
                    t0 = tm_intake_pop(digest, None)
                    if t0 is not None:
                        tm_observe(TM.ORDERED_E2E_MS, (tm_now - t0) * 1e3)
            client_id = req_clients_pop(digest, None)
            if client_id is not None and self._clients_attached:
                reply_work.append((client_id, txn, seq_no))
            if digest:
                free_request(digest)
        if ordered_pairs:
            self.monitor.requests_ordered_bulk(ordered_pairs, inst_id)
        if reply_work:
            # ONE memoized proof pass for the whole batch: the paths
            # share all upper tree nodes (merkleInfoBatch), vs an
            # independent O(log n) walk per reply
            try:
                infos = ledger.merkleInfoBatch(
                    [seq_no for _, _, seq_no in reply_work])
            except Exception:
                # one malformed entry must not strip proofs from the
                # whole batch: degrade per reply, like the old path
                logger.warning("%s: batch audit-path construction "
                               "failed; falling back per reply",
                               self.name, exc_info=True)
                infos = []
                for _, _, seq_no in reply_work:
                    try:
                        infos.append(ledger.merkleInfo(seq_no))
                    except Exception:
                        infos.append(None)
            for (client_id, txn, seq_no), info in zip(reply_work, infos):
                result = dict(txn)
                if info is not None:
                    result.update(info)
                self._reply_to_client(client_id, Reply(result=result))
        if ordered.ledgerId == POOL_LEDGER_ID:
            for txn in committed_txns or []:
                self.pool_manager.process_committed_txn(txn)

    def _on_request_rejected(self, digest: str, reason: str,
                             pp_seq_no: int):
        """A request failed dynamic validation at apply time: tell the
        waiting client (reference: Reject from _apply_pre_prepare
        rejects). Apply is SPECULATIVE (uncommitted) — a view-change
        re-order can still commit this request later, so the client
        mapping and the in-flight entry survive until the batch that
        excluded it (seq recorded here) reaches a STABLE checkpoint
        (_gc_rejected)."""
        if digest in self._rejected_digests:
            self._rejected_digests[digest] = max(
                self._rejected_digests[digest], pp_seq_no)
            return
        self._rejected_digests[digest] = pp_seq_no
        request = self._get_finalised_request(digest)
        client_id = self._req_clients.get(digest)
        if client_id is not None and request is not None:
            self._reply_to_client(client_id, Reject(
                identifier=request.identifier or "unknown",
                reqId=request.reqId or 0, reason=reason))

    def _gc_rejected(self, msg):
        """Stable checkpoint: requests rejected in batches AT OR BELOW it
        can never be re-ordered — free their in-flight state so client
        retries get answered instead of swallowed by propagator dedup.
        Rejections in still-speculative batches above the checkpoint must
        survive (a re-order may yet commit them)."""
        stable_seq = msg.last_stable_3pc[1]
        for digest in [d for d, seq in self._rejected_digests.items()
                       if seq <= stable_seq]:
            del self._rejected_digests[digest]
            self._req_clients.pop(digest, None)
            self._tm_intake_ts.pop(digest, None)
            self.propagator.requests.free(digest)

    def _committed_reply(self, request: Request) -> Optional[Reply]:
        raw = self.seq_no_db.get_or_none(request.payload_digest.encode())
        if raw is None:
            return None
        lid, seq_no = bytes(raw).decode().split(":")
        ledger = self.db_manager.get_ledger(int(lid))
        txn = ledger.getBySeqNo(int(seq_no))
        if txn is None:
            return None
        result = dict(txn)
        result.update(ledger.merkleInfo(int(seq_no)))
        return Reply(result=result)

    # ========================================================== catchup

    def start_catchup(self):
        """Stop participating, sync every ledger from peers, then resume
        (reference node.py:2610 start_catchup + §3.4)."""
        if self.leecher.in_progress:
            return
        # per-stage drain: no parsed-but-undelivered envelope may
        # straddle the catchup epoch (it would land on post-catchup
        # consensus state); re-entrant drains no-op
        self._drain_pipeline()
        logger.info("%s starting catchup", self.name)
        self.tracer.instant("catchup_start", CAT_RECOVERY)
        # pool-health bridge from the recovery lane
        self.telemetry.count(TM.CATCHUPS)
        self._catchup_started_at = __import__("time").perf_counter()
        self._catchup_started_sim = self.timer.get_current_time()
        # reads degrade gracefully: keep serving the last committed
        # (BLS-signed) roots while catchup rewrites state txn by txn
        self.db_manager.pin_read_roots()
        self.mode_participating = False
        for replica in self.replicas:
            replica.data.node_mode_participating = False
        # uncommitted work must go before catchup txns land on the
        # ledgers (reference preLedgerCatchUp: replicas revert unordered
        # batches); the pool's committed history is authoritative
        reverted = self.executor.revert_unordered_batches()
        if reverted:
            logger.info("%s reverted %d uncommitted batches for catchup",
                        self.name, reverted)
        self.replica.ordering.prepare_for_catchup()
        self.leecher.start()

    def _on_catchup_txn(self, ledger_id: int, txn: dict):
        """Apply one caught-up txn: ledger append + state update
        (reference postTxnFromCatchupAddedToLedger node.py:1748)."""
        self.metrics.add_event(MetricsName.CATCHUP_TXNS_RECEIVED, 1)
        if ledger_id == AUDIT_LEDGER_ID:
            # every audit txn records each ledger's state root at its
            # batch: feed the ts store so state-at-a-time reads resolve
            # inside caught-up history too (live nodes get these from
            # TsStoreBatchHandler at commit)
            ts_store = self.db_manager.get_store("state_ts")
            txn_time = get_txn_time(txn)
            if ts_store is not None and txn_time is not None:
                from plenum_tpu.server.batch_handlers import (
                    AUDIT_TXN_STATE_ROOT)
                roots = get_payload_data(txn).get(
                    AUDIT_TXN_STATE_ROOT) or {}
                for lid_str, root_b58 in roots.items():
                    lid = int(lid_str)
                    ledger = self.db_manager.get_ledger(lid)
                    if ledger is not None:
                        ts_store.set(txn_time,
                                     ledger.strToHash(root_b58), lid)
        from plenum_tpu.common.txn_util import get_payload_digest, get_type
        ledger = self.db_manager.get_ledger(ledger_id)
        ledger.add(dict(txn))
        txn_type = get_type(txn)
        handler = self.write_manager.request_handlers.get(txn_type)
        if handler is not None and handler.state is not None \
                and handler.ledger_id == ledger_id:
            handler.update_state(txn, None, None, is_committed=True)
            handler.state.commit()
        payload_digest = get_payload_digest(txn)
        if payload_digest:
            seq_no = get_seq_no(txn)
            self.seq_no_db.put(payload_digest.encode(),
                               "{}:{}".format(ledger_id, seq_no).encode())
        if ledger_id == POOL_LEDGER_ID:
            self.pool_manager.process_committed_txn(txn)

    def _on_catchup_finished(self):
        """Adopt 3PC position from the audit ledger, resume participating
        (reference allLedgersCaughtUp node.py:1790)."""
        # audit txns record each batch's ORIGINAL view (stable under
        # re-ordering), so the pool's CURRENT view comes from peer
        # evidence gathered during catchup (f+1-supported estimate)
        self._adopt_3pc_from_audit(
            pool_view=self.leecher.pool_view_estimate())
        # recovery over: reads resume serving the live committed roots
        # (new multi-sigs arrive with the next ordered batches) — unless
        # a view change is still pending, in which case the pin survives
        # until NewViewAccepted (ordering is paused that whole window,
        # so the caught-up roots would stay unsigned throughout it)
        if not self.replica.data.waiting_for_new_view:
            self.db_manager.unpin_read_roots()
        self.tracer.instant(
            "catchup_done", CAT_RECOVERY,
            sim_s=round(self.timer.get_current_time()
                        - getattr(self, "_catchup_started_sim",
                                  self.timer.get_current_time()), 3),
            bad_peers=len(self.leecher.bad_peers))
        if self.name not in self.pool_manager.validators:
            # catchup may have delivered our own demotion — a
            # non-validator must not resume voting
            logger.info("%s not a validator after catchup — staying "
                        "passive", self.name)
            return
        self.mode_participating = True
        for replica in self.replicas:
            replica.data.node_mode_participating = True
        self.replica.ordering.on_catchup_finished()
        if self.freshness_checker is not None:
            # stale timestamps reflect OUR absence, not the primary's
            # negligence — restart the watchdog clocks or a freshly
            # caught-up node votes out a healthy primary
            self.freshness_checker.reset_all(self.timer.get_current_time())
        started = getattr(self, "_catchup_started_at", None)
        if started is not None:
            self.metrics.add_event(
                MetricsName.CATCHUP_TIME,
                __import__("time").perf_counter() - started)
            self._catchup_started_at = None
        logger.info("%s catchup finished; last_ordered=%s", self.name,
                    self.replica.data.last_ordered_3pc)

    # ========================================================== helpers

    def _verkey_from_domain_state(self, identifier: str) -> Optional[str]:
        handler = self.write_manager.request_handlers.get(NYM)
        if handler is None or handler.state is None:
            return None
        return (handler.cached_nym_record(identifier) or {}).get(VERKEY)

    def _audit_root_at(self, pp_seq_no: int) -> str:
        """Checkpoint digest: committed audit-ledger root (all honest
        nodes have identical audit ledgers at the same pp_seq_no)."""
        audit = self.db_manager.get_ledger(AUDIT_LEDGER_ID)
        return audit.root_hash

    def _flush_telemetry(self):
        """One telemetry flush: sample the pool-health gauges (backlog
        depth, finalised-queue depth, ordering stash sizes), append a
        flush-history sample (the Perfetto counter-track time axis),
        and rewrite this node's Prometheus exposition file when
        Config.TELEMETRY_PROM_DIR is set."""
        tm = self.telemetry
        if not tm.enabled:
            return
        reqs = getattr(self.propagator, "requests", None)
        # pipeline jobs awaiting prod delivery are backlog the
        # admission ladder must see — backpressure propagates to the
        # gateway front door instead of pooling in the queue
        pipe_depth = self._pipeline.depth \
            if self._pipeline is not None else 0
        tm.gauge(TM.BACKLOG_DEPTH,
                 (len(reqs) if reqs is not None else 0) + pipe_depth)
        if self._pipeline is not None:
            tm.gauge(TM.PIPELINE_QUEUE_DEPTH, pipe_depth)
        ordering = getattr(self.replica, "ordering", None)
        if ordering is not None:
            tm.gauge(TM.REQUEST_QUEUE_DEPTH,
                     sum(len(q) for q in ordering.requestQueues.values()))
            stasher = getattr(ordering, "_stasher", None)
            if stasher is not None:
                tm.gauge(TM.STASH_DEPTH, stasher.stash_size())
        tm.flush()
        prom_dir = getattr(self.config, "TELEMETRY_PROM_DIR", None)
        if prom_dir:
            try:
                os.makedirs(prom_dir, exist_ok=True)
                tm.write_prometheus(os.path.join(
                    prom_dir, "%s.prom" % self.name.lower()))
            except OSError:
                logger.warning("%s: telemetry prom write failed",
                               self.name, exc_info=True)

    def service(self):
        """One prod tick: all protocol instances (master + backups)."""
        with self.metrics.measure_time(MetricsName.NODE_PROD_TIME):
            # any parse jobs still queued (timer starved between
            # deliveries and this tick) deliver before consensus work
            if self._pipeline is not None:
                self._pipeline.drain()
            # propagates queued this tick (intake + batch echoes) leave
            # as ONE PROPAGATE_BATCH before consensus work runs
            self.propagator.flush()
            count = self.replicas.service()
            # every instance's 3PC votes queued this tick (from
            # send_3pc_batch above AND from inbound processing since the
            # last tick) leave as ONE THREE_PC_BATCH
            if self._outbox_3pc is not None:
                self._outbox_3pc.flush()
            return count

    # ------------------------------------------------------- inspection

    @property
    def domain_ledger(self):
        return self.db_manager.get_ledger(DOMAIN_LEDGER_ID)

    @property
    def audit_ledger(self):
        return self.db_manager.get_ledger(AUDIT_LEDGER_ID)

    @property
    def last_ordered(self):
        return self.replica.last_ordered

    @property
    def view_no(self):
        return self.replica.view_no

    @property
    def master_primary_name(self):
        return self.replica.data.primary_name
