"""Transaction Author Agreement: config-ledger agreement lifecycle +
write-acceptance enforcement.

Reference: plenum/server/request_handlers/txn_author_agreement_handler.py,
txn_author_agreement_aml_handler.py, txn_author_agreement_disable_handler
.py, get_txn_author_agreement{,_aml}_handler.py, static_taa_helper.py,
and write_request_manager.py:297 (do_taa_validation).

State layout in the CONFIG MPT (same scheme as the reference's
StaticTAAHelper paths):
    taa:latest          -> digest of the active TAA ('' when disabled)
    taa:v:<version>     -> digest
    taa:d:<digest>      -> {text, version, ratification_ts[, retirement_ts]}
    taa:aml:latest      -> {version, aml, amlContext}
    taa:aml:v:<version> -> same
"""
from __future__ import annotations

from datetime import datetime, timezone
from hashlib import sha256
from typing import Optional

from plenum_tpu.common.constants import (
    AML, AML_CONTEXT, AML_VERSION, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID,
    GET_TXN_AUTHOR_AGREEMENT, GET_TXN_AUTHOR_AGREEMENT_AML,
    TAA_ACCEPTANCE_DIGEST, TAA_ACCEPTANCE_MECHANISM, TAA_ACCEPTANCE_TIME,
    TRUSTEE, TXN_AUTHOR_AGREEMENT, TXN_AUTHOR_AGREEMENT_AML,
    TXN_AUTHOR_AGREEMENT_DISABLE, TXN_AUTHOR_AGREEMENT_RATIFICATION_TS,
    TXN_AUTHOR_AGREEMENT_RETIREMENT_TS, TXN_AUTHOR_AGREEMENT_TEXT,
    TXN_AUTHOR_AGREEMENT_VERSION)
from plenum_tpu.common.exceptions import (
    InvalidClientRequest, UnauthorizedClientRequest)
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import (
    get_payload_data, get_seq_no, get_txn_time)
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.execution_lanes import TouchedKeys
from plenum_tpu.server.request_handlers import (
    ReadRequestHandler, WriteRequestHandler, decode_state_value,
    encode_state_value, nym_to_state_key)


def taa_digest(text: str, version: str) -> str:
    """sha256(version + text) hex — reference StaticTAAHelper.taa_digest."""
    return sha256((version + text).encode()).hexdigest()


def _path_latest() -> bytes:
    return b"taa:latest"


def _path_version(version: str) -> bytes:
    return "taa:v:{}".format(version).encode()


def _path_digest(digest: str) -> bytes:
    return "taa:d:{}".format(digest).encode()


def _path_aml_latest() -> bytes:
    return b"taa:aml:latest"


def _path_aml_version(version: str) -> bytes:
    return "taa:aml:v:{}".format(version).encode()


# the fixed CONFIG keys every TAA acceptance check can read (active
# digest, then the AML registry when an acceptance is present) — the
# write manager widens lane-plan declarations with these
# (WriteRequestManager.touched_keys); the acceptance-digest slot is
# per-request (_path_digest)
TAA_STATIC_READ_KEYS = ((CONFIG_LEDGER_ID, _path_latest()),
                        (CONFIG_LEDGER_ID, _path_aml_latest()))


class TaaAccess:
    """Read-side helpers over the config state (shared by handlers and
    the write manager's acceptance validation)."""

    def __init__(self, database_manager: DatabaseManager):
        self._db = database_manager

    @property
    def state(self):
        return self._db.get_state(CONFIG_LEDGER_ID)

    def _get(self, path: bytes, is_committed: bool):
        raw = self.state.get(path, isCommitted=is_committed)
        return decode_state_value(raw)

    def active_digest(self, is_committed: bool = False) -> Optional[str]:
        val, _, _ = self._get(_path_latest(), is_committed)
        digest = (val or {}).get("digest")
        return digest or None

    def digest_for_version(self, version: str,
                           is_committed: bool = False) -> Optional[str]:
        val, _, _ = self._get(_path_version(version), is_committed)
        return (val or {}).get("digest")

    def taa_by_digest(self, digest: str, is_committed: bool = False):
        """→ (data dict, seq_no, txn_time) or (None, None, None)."""
        return self._get(_path_digest(digest), is_committed)

    def aml(self, version: str = None, is_committed: bool = False):
        path = (_path_aml_latest() if version is None
                else _path_aml_version(version))
        val, seq_no, txn_time = self._get(path, is_committed)
        return val, seq_no, txn_time


class _ConfigWriteHandler(WriteRequestHandler):
    """Common TRUSTEE-only authorization for TAA writes."""

    def _require_trustee(self, request: Request):
        domain_state = self.database_manager.get_state(DOMAIN_LEDGER_ID)
        val, _, _ = decode_state_value(domain_state.get(
            nym_to_state_key(request.identifier or ""), isCommitted=False))
        if (val or {}).get("role") != TRUSTEE:
            raise UnauthorizedClientRequest(
                request.identifier, request.reqId,
                "only TRUSTEE can manage the transaction author agreement")


class TxnAuthorAgreementHandler(_ConfigWriteHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, TXN_AUTHOR_AGREEMENT,
                         CONFIG_LEDGER_ID)
        self._taa = TaaAccess(database_manager)

    def static_validation(self, request: Request):
        op = request.operation
        version = op.get(TXN_AUTHOR_AGREEMENT_VERSION)
        if not isinstance(version, str) or not version:
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "TAA must have a version")
        text = op.get(TXN_AUTHOR_AGREEMENT_TEXT)
        retirement = op.get(TXN_AUTHOR_AGREEMENT_RETIREMENT_TS)
        if text is None and retirement is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "TAA needs text (new agreement) or retirement_ts (update)")
        if text is not None and not isinstance(text, str):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "TAA text must be a string")

    def dynamic_validation(self, request: Request, req_pp_time=None):
        self._require_trustee(request)
        op = request.operation
        version = op[TXN_AUTHOR_AGREEMENT_VERSION]
        existing_digest = self._taa.digest_for_version(version)
        is_new = existing_digest is None
        if is_new:
            if op.get(TXN_AUTHOR_AGREEMENT_TEXT) is None:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "a new TAA version must include its text")
            if op.get(TXN_AUTHOR_AGREEMENT_RATIFICATION_TS) is None:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "a new TAA version must include ratification_ts")
            if op.get(TXN_AUTHOR_AGREEMENT_RETIREMENT_TS) is not None:
                # a born-retired TAA would become active yet unacceptable,
                # wedging every domain write
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "a new TAA version cannot include retirement_ts")
            aml, _, _ = self._taa.aml()
            if not aml:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "TAA cannot be set before a TAA AML is set")
        else:
            # existing version: only retirement may change (reference
            # forbids editing ratified text)
            taa_data, _, _ = self._taa.taa_by_digest(existing_digest)
            text = op.get(TXN_AUTHOR_AGREEMENT_TEXT)
            if text is not None and \
                    text != (taa_data or {}).get(TXN_AUTHOR_AGREEMENT_TEXT):
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "the text of an existing TAA version cannot change")
            ratification = op.get(TXN_AUTHOR_AGREEMENT_RATIFICATION_TS)
            if ratification is not None and ratification != \
                    (taa_data or {}).get(TXN_AUTHOR_AGREEMENT_RATIFICATION_TS):
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "ratification_ts of an existing TAA cannot change")
            if existing_digest == self._taa.active_digest() and \
                    TXN_AUTHOR_AGREEMENT_RETIREMENT_TS in op:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "the latest TAA cannot be retired; set a newer one "
                    "or send TXN_AUTHOR_AGREEMENT_DISABLE")

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        data = get_payload_data(txn)
        version = data[TXN_AUTHOR_AGREEMENT_VERSION]
        seq_no, txn_time = get_seq_no(txn), get_txn_time(txn)
        existing_digest = self._taa.digest_for_version(version)
        if existing_digest is None:
            digest = taa_digest(data[TXN_AUTHOR_AGREEMENT_TEXT], version)
            record = {
                TXN_AUTHOR_AGREEMENT_TEXT: data[TXN_AUTHOR_AGREEMENT_TEXT],
                TXN_AUTHOR_AGREEMENT_VERSION: version,
                TXN_AUTHOR_AGREEMENT_RATIFICATION_TS:
                    data.get(TXN_AUTHOR_AGREEMENT_RATIFICATION_TS),
            }
            if TXN_AUTHOR_AGREEMENT_RETIREMENT_TS in data:
                record[TXN_AUTHOR_AGREEMENT_RETIREMENT_TS] = \
                    data[TXN_AUTHOR_AGREEMENT_RETIREMENT_TS]
            self.state.set(_path_latest(), encode_state_value(
                {"digest": digest}, seq_no, txn_time))
            self.state.set(_path_version(version), encode_state_value(
                {"digest": digest}, seq_no, txn_time))
        else:
            digest = existing_digest
            record, _, _ = self._taa.taa_by_digest(digest)
            record = dict(record or {})
            if TXN_AUTHOR_AGREEMENT_RETIREMENT_TS in data:
                if data[TXN_AUTHOR_AGREEMENT_RETIREMENT_TS] is None:
                    record.pop(TXN_AUTHOR_AGREEMENT_RETIREMENT_TS, None)
                else:
                    record[TXN_AUTHOR_AGREEMENT_RETIREMENT_TS] = \
                        data[TXN_AUTHOR_AGREEMENT_RETIREMENT_TS]
        self.state.set(_path_digest(digest),
                       encode_state_value(record, seq_no, txn_time))
        return record


class TxnAuthorAgreementAmlHandler(_ConfigWriteHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, TXN_AUTHOR_AGREEMENT_AML,
                         CONFIG_LEDGER_ID)
        self._taa = TaaAccess(database_manager)

    def static_validation(self, request: Request):
        op = request.operation
        if not isinstance(op.get(AML_VERSION), str) or not op[AML_VERSION]:
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "AML must have a version")
        aml = op.get(AML)
        if not isinstance(aml, dict) or not aml:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "AML must be a non-empty mechanisms dict")

    def touched_keys(self, request: Request):
        """AML state paths are pure functions of the request (version
        string), so the handler can declare: the version slot read by
        uniqueness validation, the author's domain record, and the
        latest+versioned slots update_state writes."""
        version = request.operation.get(AML_VERSION)
        if not isinstance(version, str) or not version:
            return None
        reads = [(CONFIG_LEDGER_ID, _path_aml_version(version)),
                 (DOMAIN_LEDGER_ID,
                  nym_to_state_key(request.identifier or ""))]
        return TouchedKeys(reads=reads, writes=(
            (CONFIG_LEDGER_ID, _path_aml_latest()),
            (CONFIG_LEDGER_ID, _path_aml_version(version))))

    def dynamic_validation(self, request: Request, req_pp_time=None):
        self._require_trustee(request)
        if self._taa.aml(version=request.operation[AML_VERSION])[0]:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "AML version {} already exists".format(
                    request.operation[AML_VERSION]))

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        data = get_payload_data(txn)
        seq_no, txn_time = get_seq_no(txn), get_txn_time(txn)
        value = {AML_VERSION: data[AML_VERSION], AML: data[AML],
                 AML_CONTEXT: data.get(AML_CONTEXT)}
        encoded = encode_state_value(value, seq_no, txn_time)
        self.state.set(_path_aml_latest(), encoded)
        self.state.set(_path_aml_version(data[AML_VERSION]), encoded)
        return value


class TxnAuthorAgreementDisableHandler(_ConfigWriteHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, TXN_AUTHOR_AGREEMENT_DISABLE,
                         CONFIG_LEDGER_ID)
        self._taa = TaaAccess(database_manager)

    def static_validation(self, request: Request):
        pass

    def dynamic_validation(self, request: Request, req_pp_time=None):
        self._require_trustee(request)
        if self._taa.active_digest() is None:
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "no active TAA to disable")

    def update_state(self, txn: dict, prev_result, request: Request,
                     is_committed: bool = False):
        seq_no, txn_time = get_seq_no(txn), get_txn_time(txn)
        active = self._taa.active_digest()
        if active is not None:
            # retire the active agreement as of this txn's time
            record, _, _ = self._taa.taa_by_digest(active)
            record = dict(record or {})
            record.setdefault(TXN_AUTHOR_AGREEMENT_RETIREMENT_TS, txn_time)
            self.state.set(_path_digest(active),
                           encode_state_value(record, seq_no, txn_time))
        self.state.set(_path_latest(), encode_state_value(
            {"digest": ""}, seq_no, txn_time))
        return None


class GetTxnAuthorAgreementHandler(ReadRequestHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, GET_TXN_AUTHOR_AGREEMENT,
                         CONFIG_LEDGER_ID)
        self._taa = TaaAccess(database_manager)

    def get_result(self, request: Request) -> dict:
        op = request.operation
        digest = op.get("digest")
        if digest is None and op.get("version") is not None:
            # an unknown version must answer null, never fall back to
            # the active agreement (the client would accept wrong text)
            digest = self._taa.digest_for_version(op["version"],
                                                  is_committed=True) or ""
        if digest is None:
            digest = self._taa.active_digest(is_committed=True)
        data, seq_no, txn_time = (None, None, None)
        if digest:
            data, seq_no, txn_time = self._taa.taa_by_digest(
                digest, is_committed=True)
            if data is not None:
                data = dict(data)
                data["digest"] = digest
        return {"identifier": request.identifier, "reqId": request.reqId,
                "type": GET_TXN_AUTHOR_AGREEMENT, "data": data,
                "seqNo": seq_no, "txnTime": txn_time}


class GetTxnAuthorAgreementAmlHandler(ReadRequestHandler):
    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, GET_TXN_AUTHOR_AGREEMENT_AML,
                         CONFIG_LEDGER_ID)
        self._taa = TaaAccess(database_manager)

    def get_result(self, request: Request) -> dict:
        data, seq_no, txn_time = self._taa.aml(
            version=request.operation.get("version"), is_committed=True)
        return {"identifier": request.identifier, "reqId": request.reqId,
                "type": GET_TXN_AUTHOR_AGREEMENT_AML, "data": data,
                "seqNo": seq_no, "txnTime": txn_time}


# ------------------------------------------------- acceptance validation

class TaaAcceptanceValidator:
    """Per-write taaAcceptance enforcement (reference
    write_request_manager.py:297 do_taa_validation): required on
    TAA-protected ledgers while a TAA is active; digest must name a
    known, unretired agreement; mechanism must be in the AML; the
    acceptance time must be a whole UTC date inside
    [ratification - BEFORE, pp_time + AFTER]."""

    def __init__(self, database_manager: DatabaseManager, config):
        self._db = database_manager
        self._taa = TaaAccess(database_manager)
        self._config = config

    def validate(self, request: Request, ledger_id: int,
                 req_pp_time: int) -> None:
        acceptance = request.taaAcceptance
        if not self._db.is_taa_acceptance_required(ledger_id):
            if acceptance:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "taaAcceptance is not expected for ledger {}".format(
                        ledger_id))
            return
        active = self._taa.active_digest()
        if not active:
            if acceptance:
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    "taaAcceptance while no TAA is active")
            return
        if not acceptance:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "the active transaction author agreement must be accepted")
        digest = acceptance.get(TAA_ACCEPTANCE_DIGEST)
        taa_data, _, taa_time = self._taa.taa_by_digest(digest or "")
        if taa_data is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "unknown TAA digest {}".format(digest))
        retirement = taa_data.get(TXN_AUTHOR_AGREEMENT_RETIREMENT_TS)
        if retirement is not None and retirement < req_pp_time:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "TAA version {} is retired".format(
                    taa_data.get(TXN_AUTHOR_AGREEMENT_VERSION)))
        mechanism = acceptance.get(TAA_ACCEPTANCE_MECHANISM)
        aml, _, _ = self._taa.aml()
        if not aml or mechanism not in (aml.get(AML) or {}):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "acceptance mechanism {} is not in the AML".format(
                    mechanism))
        ts = acceptance.get(TAA_ACCEPTANCE_TIME)
        try:
            accepted = datetime.fromtimestamp(ts, tz=timezone.utc)
        except (TypeError, ValueError, OSError, OverflowError):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "malformed TAA acceptance time {!r}".format(ts))
        if (accepted.hour, accepted.minute, accepted.second,
                accepted.microsecond) != (0, 0, 0, 0):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "TAA acceptance time must be rounded to a UTC date "
                "(privacy: no sub-day precision on the ledger)")
        ratified = taa_data.get(TXN_AUTHOR_AGREEMENT_RATIFICATION_TS)
        if ratified is None:
            ratified = taa_time or 0
        lo = datetime.fromtimestamp(
            ratified - self._config.TAA_ACCEPTANCE_TIME_BEFORE_TAA,
            tz=timezone.utc).date()
        hi = datetime.fromtimestamp(
            req_pp_time + self._config.TAA_ACCEPTANCE_TIME_AFTER_PP_TIME,
            tz=timezone.utc).date()
        if not (lo <= accepted.date() <= hi):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "TAA acceptance date {} outside [{}, {}]".format(
                    accepted.date(), lo, hi))
