"""Write/Read request managers — the handler registry + batch pipeline.

Reference: plenum/server/request_managers/write_request_manager.py:33
(apply_request :148, commit_batch :178, update_state :128) and
read_request_manager.py. The write manager stages request batches onto
ledgers + MPT state (uncommitted), creates the audit txn via the batch
handler chain, and commits or reverts whole batches as 3PC decides.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from plenum_tpu.common.constants import AUDIT_LEDGER_ID
from plenum_tpu.common.exceptions import InvalidClientRequest
from plenum_tpu.common.request import Request
from plenum_tpu.common.txn_util import append_txn_metadata, reqToTxn
from plenum_tpu.server.batch_handlers import (
    AuditBatchHandler, BatchRequestHandler)
from plenum_tpu.server.database_manager import DatabaseManager
from plenum_tpu.server.request_handlers import (
    ReadRequestHandler, WriteRequestHandler)
from plenum_tpu.server.three_pc_batch import ThreePcBatch

logger = logging.getLogger(__name__)


class WriteRequestManager:
    def __init__(self, database_manager: DatabaseManager):
        from plenum_tpu.utils.metrics import (
            MetricsName, NullMetricsCollector)
        self._mn = MetricsName
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.database_manager = database_manager
        self.request_handlers: Dict[str, WriteRequestHandler] = {}
        self.batch_handlers: Dict[int, List[BatchRequestHandler]] = {}
        self.audit_b_handler: Optional[AuditBatchHandler] = None
        # TAA acceptance enforcement (reference do_taa_validation);
        # installed by NodeBootstrap.init_managers
        self.taa_validator = None
        # txn payload versioning seam (reference
        # plenum/server/txn_version_controller.py — downstream ledgers
        # override to gate validation rules on the pool version)
        from plenum_tpu.common.txn_version_controller import (
            TxnVersionController)
        self.txn_version_controller = TxnVersionController()
        # staged batches in apply order: (ledger_id, txn_count)
        self._applied_batches: List[Tuple[int, int]] = []
        # lazily-resolved TAA key helpers for touched_keys (hot lane-
        # planning path: one tuple lookup instead of two imports per
        # request)
        self._taa_key_helpers = None

    # -------------------------------------------------------- registration

    def register_req_handler(self, handler: WriteRequestHandler):
        self.request_handlers[handler.txn_type] = handler

    def register_batch_handler(self, handler: BatchRequestHandler,
                               ledger_id: Optional[int] = None):
        lid = ledger_id if ledger_id is not None else handler.ledger_id
        chain = self.batch_handlers.setdefault(lid, [])
        chain.append(handler)
        if isinstance(handler, AuditBatchHandler):
            self.audit_b_handler = handler

    def is_valid_type(self, txn_type: str) -> bool:
        return txn_type in self.request_handlers

    def type_to_ledger_id(self, txn_type: str) -> Optional[int]:
        h = self.request_handlers.get(txn_type)
        return h.ledger_id if h else None

    # --------------------------------------------------------- validation

    def static_validation(self, request: Request):
        handler = self.request_handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "unknown txn type {}".format(request.txn_type))
        handler.static_validation(request)

    def dynamic_validation(self, request: Request, req_pp_time=None):
        handler = self.request_handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "unknown txn type {}".format(request.txn_type))
        if self.taa_validator is not None and req_pp_time is not None:
            self.taa_validator.validate(request, handler.ledger_id,
                                        req_pp_time)
        self._reject_frozen_ledger_write(request, handler.ledger_id)
        handler.dynamic_validation(request, req_pp_time)

    def _reject_frozen_ledger_write(self, request: Request,
                                    ledger_id: Optional[int]):
        """Frozen ledgers accept no writes (reference ledgers_freeze/).
        Base ledgers can never be frozen (static validation), so the
        hot path skips the state lookup entirely."""
        from plenum_tpu.common.constants import (
            CONFIG_LEDGER_ID, VALID_LEDGER_IDS)
        if ledger_id is None or ledger_id in VALID_LEDGER_IDS:
            return
        from plenum_tpu.server.freeze_handlers import get_frozen_ledgers
        config_state = self.database_manager.get_state(CONFIG_LEDGER_ID)
        if config_state is None:
            return
        if ledger_id in get_frozen_ledgers(config_state,
                                           is_committed=False):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "ledger {} is frozen".format(ledger_id))

    # -------------------------------------------------------------- apply

    def apply_request(self, request: Request, batch_ts: int) -> dict:
        """Stage one request: reqToTxn, update uncommitted state, stage
        ledger txn. Returns the txn."""
        from plenum_tpu.common.constants import (
            TXN_METADATA, TXN_METADATA_SEQ_NO, TXN_METADATA_TIME)
        handler = self.request_handlers[request.txn_type]
        txn = reqToTxn(request)
        ledger = handler.ledger
        # one metadata write: seq_no + time together (append_txn_metadata
        # + append_txns_metadata used to each rebuild this dict)
        txn[TXN_METADATA] = {
            TXN_METADATA_SEQ_NO: ledger.uncommitted_size + 1,
            TXN_METADATA_TIME: batch_ts,
        }
        ledger.appendTxns([txn])
        handler.update_state(txn, None, request)
        return txn

    def ledger_id_for_request(self, request: Request) -> int:
        return self.request_handlers[request.txn_type].ledger_id

    # --------------------------------------------------- execution lanes

    def touched_keys(self, request: Request):
        """The request's declared state touches for lane planning
        (server/execution_lanes.py): the handler's own declaration
        widened by the pipeline reads dynamic_validation performs on
        the handler's behalf — TAA acceptance checks read the active
        agreement / acceptance digest / AML records out of the CONFIG
        state for every write on a TAA-protected ledger. None =
        undeclared (serial lane)."""
        handler = self.request_handlers.get(request.txn_type)
        if handler is None:
            return None
        tk = handler.touched_keys(request)
        if tk is None:
            return None
        if self.taa_validator is not None and \
                self.database_manager.is_taa_acceptance_required(
                    handler.ledger_id):
            taa = self._taa_key_helpers
            if taa is None:
                from plenum_tpu.common.constants import (
                    CONFIG_LEDGER_ID, TAA_ACCEPTANCE_DIGEST)
                from plenum_tpu.server.taa_handlers import (
                    TAA_STATIC_READ_KEYS, _path_digest)
                taa = self._taa_key_helpers = (
                    CONFIG_LEDGER_ID, TAA_ACCEPTANCE_DIGEST,
                    TAA_STATIC_READ_KEYS, TAA_STATIC_READ_KEYS[:1],
                    _path_digest)
            config_lid, digest_field, all_keys, latest_only, path = taa
            acceptance = request.taaAcceptance
            if acceptance:
                extra = list(all_keys)
                digest = acceptance.get(digest_field)
                if isinstance(digest, str):
                    extra.append((config_lid, path(digest)))
            else:
                extra = latest_only  # taa:latest only
            tk = tk.with_reads(extra)
        return tk

    def invalidate_read_caches(self, write_keys_by_ledger) -> None:
        """Lane safety: before a planned batch applies, drop every
        handler read-cache entry for a state key the batch DECLARES it
        will write (NymHandler.invalidate_for_writes) — no cached
        pre-batch record can survive into a batch that rewrites it,
        whatever order lanes resolve their reads in."""
        for lid, keys in write_keys_by_ledger.items():
            for handler in self.request_handlers.values():
                if handler.ledger_id != lid:
                    continue
                invalidate = getattr(handler, "invalidate_for_writes",
                                     None)
                if invalidate is not None:
                    invalidate(keys)

    def apply_request_deferred(self, request: Request, batch_ts: int,
                               seq_no: int) -> Tuple[dict, object]:
        """apply_request minus the ledger staging: state updates run
        now (later requests' dynamic validation must see them), the txn
        is returned with metadata for the caller to stage in ONE
        appendTxns call per batch — a per-request appendTxns([txn]) was
        measurable overhead on the apply hot path. → (txn, ledger)."""
        from plenum_tpu.common.constants import (
            TXN_METADATA, TXN_METADATA_SEQ_NO, TXN_METADATA_TIME)
        handler = self.request_handlers[request.txn_type]
        txn = reqToTxn(request)
        txn[TXN_METADATA] = {
            TXN_METADATA_SEQ_NO: seq_no,
            TXN_METADATA_TIME: batch_ts,
        }
        handler.update_state(txn, None, request)
        return txn, handler.ledger

    def post_apply_batch(self, three_pc_batch: ThreePcBatch):
        """Run the batch-handler chain after a batch's requests applied
        (audit txn creation happens here)."""
        for handler in self.batch_handlers.get(three_pc_batch.ledger_id, []):
            handler.post_batch_applied(three_pc_batch)
        for handler in self.batch_handlers.get(AUDIT_LEDGER_ID, []):
            handler.post_batch_applied(three_pc_batch)
        self._applied_batches.append(
            (three_pc_batch.ledger_id, len(three_pc_batch.valid_digests)))

    # ------------------------------------------------------------- commit

    def commit_batch(self, three_pc_batch: ThreePcBatch):
        committed = []
        with self.metrics.measure_time(self._mn.LEDGER_COMMIT_TIME):
            for handler in self.batch_handlers.get(
                    three_pc_batch.ledger_id, []):
                result = handler.commit_batch(three_pc_batch)
                if result:
                    committed = result
        with self.metrics.measure_time(self._mn.AUDIT_BATCH_TIME):
            for handler in self.batch_handlers.get(AUDIT_LEDGER_ID, []):
                handler.commit_batch(three_pc_batch)
        for txn in committed:
            self.txn_version_controller.update_version(txn)
        if self._applied_batches:
            self._applied_batches.pop(0)
        return committed

    # ------------------------------------------------------------- revert

    def post_batch_rejected(self, ledger_id: Optional[int] = None):
        """Revert the NEWEST applied batch."""
        if not self._applied_batches:
            return
        lid, count = self._applied_batches.pop()
        ledger = self.database_manager.get_ledger(lid)
        state = self.database_manager.get_state(lid)
        audit = self.database_manager.get_ledger(AUDIT_LEDGER_ID)
        if ledger is not None and count:
            ledger.discardTxns(count)
        if audit is not None and audit.uncommittedTxns:
            audit.discardTxns(1)
        self._rewind_states()

    def revert_all_uncommitted(self) -> int:
        """Revert every staged batch (view change start)."""
        n = 0
        while self._applied_batches:
            self.post_batch_rejected()
            n += 1
        return n

    def _rewind_states(self):
        """Reset every state head to match the last remaining staged batch
        (or the committed root if none): heads are recomputed from the
        audit ledger's staged entries."""
        for handler in self.request_handlers.values():
            clear = getattr(handler, "clear_caches", None)
            if clear is not None:
                clear()
        audit = self.database_manager.get_ledger(AUDIT_LEDGER_ID)
        last_roots = None
        if audit is not None and audit.uncommittedTxns:
            from plenum_tpu.common.txn_util import get_payload_data
            from plenum_tpu.server.batch_handlers import AUDIT_TXN_STATE_ROOT
            last_roots = get_payload_data(
                audit.uncommittedTxns[-1]).get(AUDIT_TXN_STATE_ROOT, {})
        for lid in self.database_manager.ledger_ids:
            if lid == AUDIT_LEDGER_ID:
                continue
            state = self.database_manager.get_state(lid)
            ledger = self.database_manager.get_ledger(lid)
            if state is None:
                continue
            if last_roots is not None and str(lid) in last_roots:
                state.revertToHead(ledger.strToHash(last_roots[str(lid)]))
            else:
                state.revertToHead(state.committedHeadHash)

    @property
    def applied_batch_count(self) -> int:
        return len(self._applied_batches)


class ActionRequestManager:
    """Actions bypass consensus: authenticated + validated, executed
    locally on the receiving node, answered directly (reference
    plenum/server/request_managers/action_request_manager.py —
    downstream ledgers register concrete handlers like POOL_RESTART;
    the framework ships the seam)."""

    def __init__(self):
        self.request_handlers: Dict[str, object] = {}

    def register_action_handler(self, handler):
        self.request_handlers[handler.txn_type] = handler

    def is_valid_type(self, txn_type: str) -> bool:
        return txn_type in self.request_handlers

    def _handler(self, request: Request):
        handler = self.request_handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "unknown action type {}".format(request.txn_type))
        return handler

    def static_validation(self, request: Request):
        self._handler(request).static_validation(request)

    def dynamic_validation(self, request: Request):
        self._handler(request).dynamic_validation(request)

    def process_action(self, request: Request) -> dict:
        return self._handler(request).process_action(request)


class ReadRequestManager:
    def __init__(self):
        self.request_handlers: Dict[str, ReadRequestHandler] = {}

    def register_req_handler(self, handler: ReadRequestHandler):
        self.request_handlers[handler.txn_type] = handler

    def is_valid_type(self, txn_type: str) -> bool:
        return txn_type in self.request_handlers

    def static_validation(self, request: Request):
        pass

    def get_result(self, request: Request) -> dict:
        handler = self.request_handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "unknown read type {}".format(request.txn_type))
        return handler.get_result(request)

    def get_results_batch(self, requests: List[Request]) -> list:
        """Serve many reads in one pass: requests are grouped per
        handler, and handlers exposing `get_results_batch` (GET_NYM —
        one batched state-engine walk for values + proofs) take whole
        groups at once; the rest answer one by one. Result slots align
        with `requests`; a slot holds the result dict OR the exception
        that request raised — per-request failures never fail the
        batch."""
        out: list = [None] * len(requests)
        groups: Dict[str, list] = {}
        for i, request in enumerate(requests):
            if request.txn_type not in self.request_handlers:
                out[i] = InvalidClientRequest(
                    request.identifier, request.reqId,
                    "unknown read type {}".format(request.txn_type))
                continue
            groups.setdefault(request.txn_type, []).append(i)
        for txn_type, idxs in groups.items():
            handler = self.request_handlers[txn_type]
            batch = getattr(handler, "get_results_batch", None)
            if batch is not None and len(idxs) > 1:
                for i, res in zip(idxs, batch([requests[i]
                                               for i in idxs])):
                    out[i] = res
                continue
            for i in idxs:
                try:
                    out[i] = handler.get_result(requests[i])
                except Exception as e:  # slot-aligned: the caller nacks
                    # this request and serves the rest of the batch
                    out[i] = e
        return out
