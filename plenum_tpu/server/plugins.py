"""Plugin seams: notifier event push + directory-loaded typed plugins.

Two extension points the reference exposes and operators rely on:

* **Notifier plugins** push operational events (cluster degraded, node
  restart, suspicious request/throughput spikes) to external systems —
  reference: plenum/server/notifier_plugin_manager.py:24 (PluginManager,
  pip-discovered by the ``indynotifier`` name prefix) with EMA-based
  spike detection at :55 (sendMessageUponSuspiciousSpike). A notifier
  plugin is anything with ``send_message(topic, message)``.

* **Typed plugins** are classes loaded from a directory whose
  ``plugin_type`` attribute names a seam — reference:
  plenum/server/plugin_loader.py:25 (PluginLoader scans ``plugin*.py``
  files for classes with a ``pluginType`` attr) and
  plenum/common/plugin_helper.py:12 (loadPlugins by explicit name).
  VERIFICATION plugins veto client operations (their ``verify(op)``
  raises to reject); STATS_CONSUMER plugins receive periodic stats.

Redesign vs the reference: no module-level singleton (the manager is
node-owned so tests and multi-node processes don't share state), no
``sys.path`` mutation (modules load via importlib specs), and discovery
is directory/explicit-object based — this image has no pip entry-point
ecosystem to scan.
"""
from __future__ import annotations

import importlib.util
import logging
import math
import re
import sys
import time
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

PLUGIN_TYPE_VERIFICATION = "VERIFICATION"
PLUGIN_TYPE_STATS_CONSUMER = "STATS_CONSUMER"
VALID_PLUGIN_TYPES = (PLUGIN_TYPE_VERIFICATION, PLUGIN_TYPE_STATS_CONSUMER)

# canonical topic strings (reference notifierPluginTriggerEvents)
TOPIC_CLUSTER_DEGRADED = "ClusterDegraded"
TOPIC_CLUSTER_RESTART = "ClusterRestart"
TOPIC_NODE_REQUEST_SPIKE = "NodeRequestSuspiciousSpike"
TOPIC_CLUSTER_THROUGHPUT_SPIKE = "ClusterThroughputSuspiciousSpike"


def _load_module_from_file(path: Path):
    """Import one file as a uniquely-named module without touching
    sys.path (plugin dirs must not shadow stdlib names)."""
    # crc32, not hash(): str hashes are PYTHONHASHSEED-salted, so the
    # module name would differ per process (PT012 audit; the PR-7
    # catchup-jitter precedent) — crc32 keeps names stable across
    # replicas and restarts
    mod_name = "plenum_tpu_plugin_%s_%x" % (
        path.stem, zlib.crc32(str(path).encode()))
    if mod_name in sys.modules:
        return sys.modules[mod_name]
    spec = importlib.util.spec_from_file_location(mod_name, path)
    if spec is None or spec.loader is None:
        raise ImportError("cannot build import spec for %s" % path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = module
    spec.loader.exec_module(module)
    return module


class SpikeDetector:
    """EMA anomaly detector for one metric stream (reference
    notifier_plugin_manager.py:55 sendMessageUponSuspiciousSpike keeps
    the same state inline): tracks an exponential moving average; a new
    sample outside ``[ema/coeff, ema*coeff]`` after warm-up is a spike.
    With ``use_weighted_bounds_coeff`` the band narrows as log10(cnt)
    grows — long-lived averages earn tighter alarms."""

    def __init__(self, min_cnt: int = 15, bounds_coeff: float = 10,
                 min_activity_threshold: float = 10,
                 use_weighted_bounds_coeff: bool = True,
                 enabled: bool = True):
        self.min_cnt = min_cnt
        self.bounds_coeff = bounds_coeff
        self.min_activity_threshold = min_activity_threshold
        self.use_weighted_bounds_coeff = use_weighted_bounds_coeff
        self.enabled = enabled
        self.value = 0.0
        self.cnt = 0

    @classmethod
    def from_config(cls, cfg: Dict) -> "SpikeDetector":
        return cls(min_cnt=cfg.get("min_cnt", 15),
                   bounds_coeff=cfg.get("bounds_coeff", 10),
                   min_activity_threshold=cfg.get(
                       "min_activity_threshold", 10),
                   use_weighted_bounds_coeff=cfg.get(
                       "use_weighted_bounds_coeff", True),
                   enabled=cfg.get("enabled", True))

    def observe(self, new_val: float) -> Optional[Dict]:
        """Feed one sample. Returns a spike-description dict when the
        sample breaks the adaptive bounds, else None. The EMA absorbs
        the sample either way (an alarm must not freeze the average the
        way skipping the update would)."""
        if not self.enabled:
            return None
        prev = self.value
        alpha = 2.0 / (self.min_cnt + 1)
        self.value = prev * (1 - alpha) + new_val * alpha
        self.cnt += 1
        if self.cnt <= self.min_cnt:
            return None  # still warming up
        if prev < self.min_activity_threshold:
            return None  # too quiet for bounds to mean anything
        coeff = self.bounds_coeff
        if self.use_weighted_bounds_coeff and self.cnt > 10:
            coeff /= math.log10(self.cnt)
        lo, hi = prev / coeff, prev * coeff
        if lo <= new_val <= hi:
            return None
        return {"actual": new_val, "expected": prev,
                "bounds": [lo, hi], "cnt": self.cnt}


class NotifierPluginManager:
    """Fans operational events out to registered notifier plugins.

    A plugin is any object (usually a module) exposing
    ``send_message(topic: str, message: str)``. A failing plugin is
    logged and skipped — observers must never take the node down.
    Reference: plenum/server/notifier_plugin_manager.py:139
    (_sendMessage fan-out with the same isolation guarantee).
    """

    def __init__(self, node_name: str = "", enabled: bool = True,
                 spike_configs: Optional[Dict[str, Dict]] = None):
        self.node_name = node_name
        self.enabled = enabled
        self.plugins: List[Any] = []
        self._detectors: Dict[str, SpikeDetector] = {}
        for topic, cfg in (spike_configs or {}).items():
            self._detectors[topic] = SpikeDetector.from_config(cfg)
        self.sent = 0  # events delivered (sum over plugins)

    # ------------------------------------------------------- registration

    def register(self, plugin: Any) -> None:
        if not callable(getattr(plugin, "send_message", None)):
            raise TypeError(
                "notifier plugin %r has no send_message(topic, message)"
                % (plugin,))
        self.plugins.append(plugin)

    def load_from_dir(self, path) -> int:
        """Import every ``notifier*.py`` / ``plugin*.py`` file in `path`
        that exposes a module-level send_message. → count loaded."""
        p = Path(path)
        if not p.is_dir():
            return 0
        n = 0
        pat = re.compile(r"^(notifier|plugin).*\.py$", re.IGNORECASE)
        for f in sorted(p.iterdir()):
            if not (f.is_file() and pat.match(f.name)):
                continue
            try:
                module = _load_module_from_file(f)
            except Exception:
                logger.error("notifier plugin %s failed to import", f,
                             exc_info=True)
                continue
            if callable(getattr(module, "send_message", None)):
                self.plugins.append(module)
                n += 1
                logger.info("loaded notifier plugin %s", f.name)
        return n

    # ------------------------------------------------------------- events

    def send(self, topic: str, message: str) -> int:
        """Deliver to every plugin; → successful deliveries."""
        if not self.enabled:
            return 0
        ok = 0
        for plugin in self.plugins:
            try:
                plugin.send_message(topic, message)
                ok += 1
            except Exception:
                logger.error("notifier plugin %r failed on %s",
                             plugin, topic, exc_info=True)
        self.sent += ok
        return ok

    def send_cluster_degraded(self, reason: str = "") -> int:
        return self.send(
            TOPIC_CLUSTER_DEGRADED,
            "Cluster performance degraded on node %s at %s: %s"
            % (self.node_name, time.time(), reason or "master throughput "
               "below threshold; voting for view change"))

    def send_cluster_restart(self, detail: str = "") -> int:
        return self.send(
            TOPIC_CLUSTER_RESTART,
            "Node %s restarted from persisted state at %s. %s"
            % (self.node_name, time.time(), detail))

    def send_spike_check(self, topic: str, new_val: float) -> int:
        """Feed one periodic sample to the topic's detector; pushes an
        event only when the detector flags it (reference :55)."""
        det = self._detectors.get(topic)
        if det is None or not self.enabled:
            return 0
        spike = det.observe(new_val)
        if spike is None:
            return 0
        return self.send(
            topic,
            "%s on node %s at %s. Actual: %s. Expected: %s. "
            "Bounds: [%s, %s]." % (topic, self.node_name, time.time(),
                                   spike["actual"], spike["expected"],
                                   spike["bounds"][0], spike["bounds"][1]))


class PluginLoader:
    """Loads typed plugin classes from a directory.

    Scans for ``plugin*.py`` files, imports each, instantiates every
    class carrying a ``plugin_type`` attribute naming a valid seam, and
    groups instances by type. Reference: plenum/server/plugin_loader.py:25
    (same file-pattern + class-attribute discovery contract; this one
    imports via specs instead of sys.path insertion and accepts the
    reference's camelCase ``pluginType`` spelling too).
    """

    def __init__(self, path):
        if not path:
            raise ValueError("plugin path is required")
        self.path = Path(path)
        self.plugins: Dict[str, List[Any]] = {}
        self._load()

    def get(self, type_name: str) -> List[Any]:
        return self.plugins.get(type_name, [])

    def _load(self):
        if not self.path.is_dir():
            logger.warning("plugin dir %s does not exist", self.path)
            return
        pat = re.compile(r"^[pP]lugin.*\.py$")
        for f in sorted(self.path.iterdir()):
            if not (f.is_file() and pat.match(f.name)):
                continue
            try:
                module = _load_module_from_file(f)
            except Exception:
                logger.error("plugin module %s failed to import", f,
                             exc_info=True)
                continue
            for obj in vars(module).values():
                if not isinstance(obj, type):
                    continue
                if obj.__module__ != module.__name__:
                    continue  # imported, not defined here — a shared
                    # base class must not be instantiated per importer
                ptype = getattr(obj, "plugin_type",
                                getattr(obj, "pluginType", None))
                if ptype is None:
                    continue
                if ptype not in VALID_PLUGIN_TYPES:
                    logger.warning(
                        "skipping plugin class %s: invalid plugin_type "
                        "%r (valid: %s)", obj.__name__, ptype,
                        VALID_PLUGIN_TYPES)
                    continue
                try:
                    inst = obj()
                except Exception:
                    logger.error("plugin class %s failed to construct",
                                 obj.__name__, exc_info=True)
                    continue
                self.plugins.setdefault(ptype, []).append(inst)
                logger.info("loaded %s plugin %s from %s", ptype,
                            obj.__name__, f.name)
