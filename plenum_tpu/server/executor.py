"""NodeBatchExecutor — the real BatchExecutor over ledgers + MPT state.

Bridges OrderingService (which speaks request digests and roots) to the
WriteRequestManager pipeline (reference: the Node.executeBatch /
apply_reqs glue, plenum/server/node.py:2661 + ordering_service
create_3pc_batch). Replaces SimExecutor in full-node pools.

Shard-parallel deterministic execution (docs/execution.md): each
ordered batch runs through three sub-stages, each its own flight-
recorder span so ``scripts/trace_budget`` attributes the execute
budget line by line:

* ``exec_validate`` — resolve every request, collect the handlers'
  declared state touches (``WriteRequestHandler.touched_keys``),
  partition the batch into deterministic execution lanes (union-find
  over shared keys, server/execution_lanes.py), pre-invalidate handler
  read caches for the batch's declared writes, and prefetch every
  declared read key's pre-batch value in ONE deduplicated walk per
  state (``PruningState.begin_read_window``).
* ``lane_apply`` — the per-request validate→apply stream in batch
  order (the canonical schedule every schedule must be byte-equal to);
  validation reads are dict hits against pending-buffer + read window.
* ``hash_resolve`` — ONE merged hash resolution for every state the
  batch wrote (``flush_states_merged``: per-state bulk structural
  merge, then all states' dirty nodes hashed in shared level-wise
  SHA3 dispatches), overlapped with the ledger leaf-hash launches and
  the verifier-hub kick inside the fused device window.

Lane assignment is a pure function of the ordered batch — every honest
node partitions identically — and the applied state is a function of
batch order alone, so lanes can never diverge roots (tests +
bench gate assert byte-equality against the serial path).
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from plenum_tpu.common.constants import AUDIT_LEDGER_ID
from plenum_tpu.common.messages.node_messages import Ordered
from plenum_tpu.common.request import Request
from plenum_tpu.consensus.ordering_service import BatchExecutor
from plenum_tpu.observability.tracing import (
    CAT_DEVICE, CAT_EXECUTE, NullTracer)
from plenum_tpu.observability.telemetry import TM, NullTelemetryHub
from plenum_tpu.server.execution_lanes import exec_fanout, plan_lanes
from plenum_tpu.server.three_pc_batch import ThreePcBatch
from plenum_tpu.server.write_request_manager import WriteRequestManager
from plenum_tpu.state.pruning_state import flush_states_merged
from plenum_tpu.utils.metrics import MetricsName, NullMetricsCollector

logger = logging.getLogger(__name__)


class NodeBatchExecutor(BatchExecutor):
    def __init__(self, write_manager: WriteRequestManager,
                 requests_source: Callable[[str], Optional[Request]],
                 get_view_no: Callable[[], int] = None,
                 primaries_for_view: Callable[[int], List[str]] = None,
                 get_pp_seq_no: Callable[[], int] = None,
                 on_batch_committed: Callable = None,
                 on_request_rejected: Callable[[str, str, int],
                                               None] = None,
                 fused_dispatch: bool = True,
                 device_kick: Callable[[], None] = None,
                 lanes: bool = None, lane_min: int = None):
        """requests_source(digest) → Request (the propagator's store).
        get_pp_seq_no() → seq of the batch being applied NOW (the
        ordering service's apply position + 1) — must survive catchup
        fast-forwards and view changes, so it cannot be a local counter.
        primaries_for_view(view_no) → primaries of that view — keyed by
        the batch's ORIGINAL view so re-applied batches reproduce the
        same audit txn (reference PrimaryBatchHandler.post_batch_applied
        selects primaries from three_pc_batch.original_view_no).
        lanes/lane_min: conflict-lane execution (Config.EXEC_LANES /
        EXEC_LANE_MIN when None)."""
        from plenum_tpu.common.config import Config
        self.write_manager = write_manager
        self._requests_source = requests_source
        self.metrics = NullMetricsCollector()  # node injects the real one
        self.tracer = NullTracer()             # node injects the real one
        self.telemetry = NullTelemetryHub()    # node injects the real one
        self._get_view_no = get_view_no or (lambda: 0)
        self._primaries_for_view = primaries_for_view or (lambda v: [])
        self._get_pp_seq_no = get_pp_seq_no
        self._pp_seq_no = 0
        self._on_batch_committed = on_batch_committed
        self._on_request_rejected = on_request_rejected or \
            (lambda d, r, s: None)
        # pipeline execution fan-out (set_exec_map); None = serial
        self._exec_map = None
        # fused per-3PC-batch device dispatch (Config.FUSED_BATCH_
        # DISPATCH): the batch's ledger leaf-hash launch, a verifier-hub
        # kick, and the MPT pending-apply share ONE overlapped device
        # window per applied batch instead of serialized round trips.
        # device_kick() flushes whatever verify generation is queued
        # (CoalescingVerifierHub) into that same window.
        self._fused = fused_dispatch
        self._device_kick = device_kick
        self._lanes = getattr(Config, "EXEC_LANES", True) \
            if lanes is None else lanes
        self._lane_min = getattr(Config, "EXEC_LANE_MIN", 8) \
            if lane_min is None else lane_min
        # staged batches by apply order (mirrors write manager staging)
        self._staged: List[ThreePcBatch] = []
        # runtime ownership sanitizer (node-injected): lane planning
        # and batch commit are prod-thread seams — exec_map fans ITEMS
        # to pool threads, but the plan/commit decisions stay owned
        self._sanitizer = None

    def set_sanitizer(self, sanitizer) -> None:
        self._sanitizer = sanitizer

    @property
    def db(self):
        return self.write_manager.database_manager

    def _next_pp_seq_no(self) -> int:
        """Seq number of the batch being applied NOW: the ordering
        service's position when wired, the local counter's successor in
        standalone use (bench/tests) — single-sourced for the reject
        path and the post-apply advance."""
        return self._get_pp_seq_no() if self._get_pp_seq_no is not None \
            else self._pp_seq_no + 1

    # -------------------------------------------------------------- apply

    def apply_batch(self, pre_prepare_digests: List[str], ledger_id: int,
                    pp_time: int, pp_digest: str = "",
                    original_view_no: int = None) -> Tuple[str, str, str]:
        with self.metrics.measure_time(MetricsName.BATCH_APPLY_TIME), \
                self.telemetry.timer(TM.STAGE_EXECUTE_MS), \
                self.tracer.span("batch_apply", CAT_EXECUTE,
                                 key=pp_digest or None,
                                 batch_size=len(pre_prepare_digests),
                                 ledger_id=ledger_id):
            return self._apply_batch(pre_prepare_digests, ledger_id,
                                     pp_time, pp_digest, original_view_no)

    def _plan_and_prefetch(self, requests: List[Request], key: str,
                           windows: List):
        """exec_validate sub-stage: declared touches → lane plan →
        cache pre-invalidation → one read-window prefetch per touched
        state. Installed windows append to the CALLER's `windows` list
        as they open, so the caller's finally closes every window even
        when a later prefetch raises mid-way. → the lane plan."""
        touched = self.write_manager.touched_keys
        if self._sanitizer is not None:
            self._sanitizer.check("lane planner")
        with self.tracer.span("exec_validate", CAT_EXECUTE, key=key,
                              batch_size=len(requests)) as sp:
            plan = plan_lanes([touched(r) for r in requests])
            self.telemetry.observe(TM.EXEC_LANES_PER_BATCH, plan.n_lanes)
            self.telemetry.observe(TM.EXEC_CONFLICT_PCT,
                                   plan.conflict_ratio * 100.0)
            if plan.serial_requests:
                self.telemetry.count(TM.EXEC_SERIAL_FALLBACK,
                                     plan.serial_requests)
            self.write_manager.invalidate_read_caches(
                plan.write_keys_by_ledger)
            for lid, keys in plan.read_keys_by_ledger.items():
                state = self.db.get_state(lid)
                if state is not None and state.begin_read_window(keys):
                    windows.append(state)
            sp.add(lanes=plan.n_lanes, serial=plan.serial_requests)
        return plan

    def _apply_batch(self, pre_prepare_digests: List[str], ledger_id: int,
                     pp_time: int, pp_digest: str = "",
                     original_view_no: int = None) -> Tuple[str, str, str]:
        ledger = self.db.get_ledger(ledger_id)
        state = self.db.get_state(ledger_id)
        requests: List[Request] = []
        for digest in pre_prepare_digests:
            request = self._requests_source(digest)
            if request is None:
                raise KeyError(
                    "request {} not available for apply".format(digest))
            requests.append(request)
        plan = None
        windows: List = []
        valid = []
        # state updates happen per request (later requests' validation
        # must see them), but the ledger staging of the whole batch is
        # ONE appendTxns call at the end — txns group by their
        # handler's ledger (one group for a normal per-ledger batch)
        staged: Dict[int, List[dict]] = {}
        seq_base: Dict[int, int] = {}
        validate = self.write_manager.dynamic_validation
        apply_deferred = self.write_manager.apply_request_deferred
        try:
            if self._lanes and len(requests) >= self._lane_min:
                plan = self._plan_and_prefetch(
                    requests, pp_digest or None, windows)
            with self.tracer.span(
                    "lane_apply", CAT_EXECUTE, key=pp_digest or None,
                    batch_size=len(requests),
                    lanes=plan.n_lanes if plan else 0):
                # batch order is the canonical schedule: every request
                # observes exactly the writes ordered before it (reads
                # go pending-buffer → read window → trie), so the lane
                # machinery can never diverge from serial semantics
                for digest, request in zip(pre_prepare_digests, requests):
                    try:
                        validate(request, pp_time)
                    except Exception as e:
                        logger.info(
                            "request %s failed dynamic validation: %s",
                            digest, e)
                        self._on_request_rejected(
                            digest, str(e), self._next_pp_seq_no())
                        continue
                    handler_lid = self.write_manager.ledger_id_for_request(
                        request)
                    group = staged.get(handler_lid)
                    if group is None:
                        group = staged[handler_lid] = []
                        seq_base[handler_lid] = self.db.get_ledger(
                            handler_lid).uncommitted_size
                    txn, _lgr = apply_deferred(
                        request, pp_time,
                        seq_base[handler_lid] + len(group) + 1)
                    group.append(txn)
                    valid.append(digest)
        finally:
            for st in windows:
                st.end_read_window()
        with self.tracer.span("hash_resolve", CAT_EXECUTE,
                              key=pp_digest or None, groups=len(staged)):
            state_root = self._stage_and_resolve(staged, state, ledger,
                                                 len(valid), pp_digest)
        self._pp_seq_no = self._next_pp_seq_no()
        txn_root = ledger.hashToStr(ledger.uncommitted_root_hash)
        view_no = self._get_view_no()
        ov = original_view_no if original_view_no is not None else view_no
        batch = ThreePcBatch(
            ledger_id=ledger_id,
            inst_id=0,
            view_no=view_no,
            pp_seq_no=self._pp_seq_no,
            pp_time=pp_time,
            state_root=state_root,
            txn_root=txn_root,
            valid_digests=valid,
            pp_digest=pp_digest,
            primaries=self._primaries_for_view(ov),
            original_view_no=ov,
        )
        self.write_manager.post_apply_batch(batch)
        self._staged.append(batch)
        audit = self.db.get_ledger(AUDIT_LEDGER_ID)
        audit_root = audit.hashToStr(audit.uncommitted_root_hash)
        return state_root, txn_root, audit_root

    def _stage_and_resolve(self, staged: Dict[int, List[dict]], state,
                           ledger, n_valid: int, pp_digest: str) -> str:
        """hash_resolve sub-stage: stage every ledger group's txns and
        resolve every written state's dirty trie nodes in ONE merged
        level-wise pass, all inside the fused device window."""
        if self._fused and staged:
            # FUSED per-batch device window: launch every ledger group's
            # leaf-hash dispatch, kick the verifier hub's queued
            # generation into the same window, run the merged MPT
            # pending-resolve (per-state bulk structural merge + shared
            # level-wise hash dispatches across ALL written states)
            # WHILE those launches are in flight, then collect the
            # staged hashes — one overlapped round trip where the
            # per-message path serialized them. Results are
            # bit-identical: the streams touch disjoint structures and
            # each collect point is unchanged.
            with self.telemetry.timer(TM.STAGE_DISPATCH_MS), \
                    self.tracer.span(
                    "fused_dispatch", CAT_DEVICE, key=pp_digest or None,
                    groups=len(staged), batch_size=n_valid):
                in_flight = [
                    (lid, self.db.get_ledger(lid).stage_txns_dispatch(
                        txns))
                    for lid, txns in staged.items()]
                if self._device_kick is not None:
                    self._device_kick()
                state_root = self._resolve_states(staged, state, ledger)
                for lid, handle in in_flight:
                    self.db.get_ledger(lid).stage_txns_collect(handle)
        else:
            for lid, txns in staged.items():
                self.db.get_ledger(lid).appendTxns(txns)
            state_root = self._resolve_states(staged, state, ledger)
        return state_root

    def set_exec_map(self, fn) -> None:
        """Install the pipeline's execution fan-out: an
        order-preserving parallel map the merged state flush uses to
        run independent per-state structural merges concurrently
        (runtime/pipeline.py exec_map). None/unset = serial, the
        validated fallback."""
        self._exec_map = fn

    def _resolve_states(self, staged: Dict[int, List[dict]], state,
                        ledger) -> str:
        """Merge every written state's hash resolution (lanes and
        ledgers share the level-wise dispatches); the batch ledger's
        head read afterwards is a no-op flush."""
        if self._lanes and staged:
            lanes_fan = exec_fanout(len(staged))
            flush_states_merged(
                [self.db.get_state(lid) for lid in staged],
                exec_map=self._exec_map if lanes_fan > 1 else None)
        return ledger.hashToStr(state.headHash) if state else ""

    # ------------------------------------------------------------- revert

    def revert_unordered_batches(self) -> int:
        n = self.write_manager.revert_all_uncommitted()
        self._staged = []
        if self._get_pp_seq_no is None:
            self._pp_seq_no -= n
        return n

    def revert_last_batch(self):
        if self._staged:
            self._staged.pop()
            self.write_manager.post_batch_rejected()
            if self._get_pp_seq_no is None:
                self._pp_seq_no -= 1

    # ------------------------------------------------------------- commit

    def commit_batch(self, ordered: Ordered):
        with self.metrics.measure_time(MetricsName.BATCH_COMMIT_TIME), \
                self.telemetry.timer(TM.STAGE_COMMIT_MS), \
                self.tracer.span(
                    "batch_commit", CAT_EXECUTE,
                    key="%d:%d" % (ordered.viewNo, ordered.ppSeqNo),
                    batch_size=len(ordered.valid_reqIdr)):
            return self._commit_batch(ordered)

    def _commit_batch(self, ordered: Ordered):
        if self._sanitizer is not None:
            self._sanitizer.check("state pending buffers")
        if not self._staged:
            logger.warning("commit with no staged batch at %s",
                           (ordered.viewNo, ordered.ppSeqNo))
            return
        batch = self._staged.pop(0)
        if batch.pp_digest and ordered.digest and \
                batch.pp_digest != ordered.digest:
            logger.warning("ordered digest %s != staged batch digest %s at %s",
                           ordered.digest, batch.pp_digest,
                           (ordered.viewNo, ordered.ppSeqNo))
        committed = self.write_manager.commit_batch(batch)
        # free ordered requests from the in-flight store
        if self._on_batch_committed is not None:
            self._on_batch_committed(ordered, committed)

    # -------------------------------------------------------------- reads

    def is_request_known(self, digest: str) -> bool:
        return self._requests_source(digest) is not None
