from plenum_tpu.utils.util import (  # noqa: F401
    max_faulty,
    check_if_more_than_f_same_items,
    random_string,
    hex_to_bytes,
    pop_keys,
    get_utc_epoch,
    first,
    update_named_tuple,
)
