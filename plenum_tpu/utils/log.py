"""Logging subsystem: custom levels + compressed rotating file logs.

Reference: stp_core/common/log.py:29 (Singleton Logger, TRACE(5) and
DISPLAY(25) custom levels) + stp_core/common/logging/
CompressingFileHandler.py (rotating file handler that gzips rotated
segments). An operator running `start_plenum_tpu_node` for weeks needs
bounded, greppable, per-node log files; TRACE gives message-level wire
debugging below DEBUG, DISPLAY sits between INFO and WARNING for
operator-facing progress lines that must survive a quieter-than-INFO
configuration.
"""
from __future__ import annotations

import gzip
import logging
import logging.handlers
import os
import shutil
from typing import Optional

TRACE = 5
DISPLAY = 25

logging.addLevelName(TRACE, "TRACE")
logging.addLevelName(DISPLAY, "DISPLAY")


def _trace(self, msg, *args, **kwargs):
    if self.isEnabledFor(TRACE):
        self._log(TRACE, msg, args, **kwargs)


def _display(self, msg, *args, **kwargs):
    if self.isEnabledFor(DISPLAY):
        self._log(DISPLAY, msg, args, **kwargs)


# reference log.py injects the level methods on Logger once, globally
if not hasattr(logging.Logger, "trace"):
    logging.Logger.trace = _trace
if not hasattr(logging.Logger, "display"):
    logging.Logger.display = _display


class CompressingFileHandler(logging.handlers.RotatingFileHandler):
    """RotatingFileHandler whose rotated segments are gzip-compressed —
    node logs compress ~20x, so backupCount segments cover weeks instead
    of hours for the same disk budget (reference
    CompressingFileHandler.py)."""

    def __init__(self, filename, maxBytes: int = 50 * 1024 * 1024,
                 backupCount: int = 10, encoding=None, delay=False):
        super().__init__(filename, maxBytes=maxBytes,
                         backupCount=backupCount, encoding=encoding,
                         delay=delay)

    def rotation_filename(self, default_name: str) -> str:  # noqa: N802
        return default_name + ".gz"

    def rotate(self, source: str, dest: str) -> None:
        try:
            with open(source, "rb") as f_in, \
                    gzip.open(dest, "wb") as f_out:
                shutil.copyfileobj(f_in, f_out)
            os.remove(source)
        except OSError:  # rotation must never kill the node
            logging.getLogger(__name__).warning(
                "log rotation %s -> %s failed", source, dest, exc_info=True)


DEFAULT_FORMAT = ("%(asctime)s | %(levelname)-8s | %(name)s "
                  "(%(filename)s:%(lineno)d) | %(message)s")


class Logger:
    """Process-wide logging configurator (reference log.py Singleton).

    Usage:
        Logger().enableFileLogging("/var/log/plenum_tpu/Alpha.log")
        Logger().enableStdLogging()
        Logger().setLevel(TRACE)
    """

    _instance = None

    def __new__(cls, *args, **kwargs):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._file_handler = None
            cls._instance._console_handler = None
            cls._instance._format = DEFAULT_FORMAT
        return cls._instance

    @property
    def _root(self) -> logging.Logger:
        return logging.getLogger()

    def setLevel(self, level) -> None:  # noqa: N802
        self._root.setLevel(level)

    def apply_config(self, config) -> None:
        """Pick up logging_level / logging_format from a node Config."""
        fmt = getattr(config, "LOG_FORMAT", None)
        if fmt:
            self._format = fmt
            for h in (self._file_handler, self._console_handler):
                if h is not None:
                    h.setFormatter(logging.Formatter(fmt))
        level = getattr(config, "LOG_LEVEL", None)
        if level is not None:
            self.setLevel(level)

    def enableStdLogging(self) -> None:  # noqa: N802
        if self._console_handler is None:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter(self._format))
            self._console_handler = h
            self._root.addHandler(h)

    def enableFileLogging(self, file_path: str,
                          max_bytes: int = 50 * 1024 * 1024,
                          backup_count: int = 10) -> None:  # noqa: N802
        if self._file_handler is not None:
            return
        os.makedirs(os.path.dirname(os.path.abspath(file_path)),
                    exist_ok=True)
        h = CompressingFileHandler(file_path, maxBytes=max_bytes,
                                   backupCount=backup_count)
        h.setFormatter(logging.Formatter(self._format))
        self._file_handler = h
        self._root.addHandler(h)

    def disableFileLogging(self) -> None:  # noqa: N802
        if self._file_handler is not None:
            self._root.removeHandler(self._file_handler)
            self._file_handler.close()
            self._file_handler = None

    @property
    def log_file(self) -> Optional[str]:
        return (self._file_handler.baseFilename
                if self._file_handler else None)


def getlogger(name: Optional[str] = None) -> logging.Logger:
    """Reference-parity accessor (stp_core getlogger)."""
    return logging.getLogger(name)
