"""Minimal stdlib stand-ins for the `sortedcontainers` types this repo
uses (SortedList/SortedSet/SortedDict), built on `bisect`.

The real library is a soft dependency: when importable it is used
unchanged (its amortized splits beat plain `insort` on huge
collections); when absent — some deployment images ship without it —
these fallbacks keep the runtime/storage layers importable with the
same semantics for the small API surface actually exercised here
(add/discard/pop/irange/items). O(n) inserts are acceptable at the
sizes involved: stash replay queues and KV iteration indexes."""
import bisect
from typing import Any, Callable, Iterable, Optional


class SortedList:
    """add / pop(0) / len / iter, with an optional key function —
    exactly what SortedStash needs."""

    def __init__(self, iterable: Iterable = (),
                 key: Optional[Callable] = None):
        self._key = key or (lambda x: x)
        self._keys = []
        self._items = []
        for item in iterable:
            self.add(item)

    def add(self, item: Any) -> None:
        k = self._key(item)
        idx = bisect.bisect_right(self._keys, k)
        self._keys.insert(idx, k)
        self._items.insert(idx, item)

    def pop(self, index: int = -1) -> Any:
        self._keys.pop(index)
        return self._items.pop(index)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)


class SortedSet:
    def __init__(self, iterable: Iterable = ()):
        self._keys = sorted(set(iterable))
        self._set = set(self._keys)

    def add(self, key: Any) -> None:
        if key not in self._set:
            self._set.add(key)
            bisect.insort(self._keys, key)

    def discard(self, key: Any) -> None:
        if key in self._set:
            self._set.remove(key)
            self._keys.remove(key)

    def irange(self, minimum=None, maximum=None):
        lo = 0 if minimum is None else bisect.bisect_left(self._keys, minimum)
        hi = len(self._keys) if maximum is None \
            else bisect.bisect_right(self._keys, maximum)
        return iter(self._keys[lo:hi])

    def __contains__(self, key: Any) -> bool:
        return key in self._set

    def __len__(self) -> int:
        return len(self._keys)

    def __iter__(self):
        return iter(self._keys)


class SortedDict(dict):
    """dict with key-ordered iteration, items() and irange()."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._sorted = sorted(super().keys())

    def __setitem__(self, key, value):
        if key not in self:
            bisect.insort(self._sorted, key)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        super().__delitem__(key)
        self._sorted.remove(key)

    def pop(self, key, *default):
        if key in self:
            self._sorted.remove(key)
        return super().pop(key, *default)

    def clear(self):
        super().clear()
        self._sorted = []

    def setdefault(self, key, default=None):
        if key not in self:
            self[key] = default
            return default
        return self[key]

    def update(self, *args, **kwargs):
        for k, v in dict(*args, **kwargs).items():
            self[k] = v

    def irange(self, minimum=None, maximum=None):
        lo = 0 if minimum is None \
            else bisect.bisect_left(self._sorted, minimum)
        hi = len(self._sorted) if maximum is None \
            else bisect.bisect_right(self._sorted, maximum)
        return iter(self._sorted[lo:hi])

    def keys(self):
        return list(self._sorted)

    def items(self):
        return [(k, self[k]) for k in self._sorted]

    def values(self):
        return [self[k] for k in self._sorted]

    def __iter__(self):
        return iter(self._sorted)
