"""Shared circuit-breaker policy for attach-behind device engines.

Two subsystems attach an optional device engine behind a host
implementation (`CompactMerkleTree.attach_device_engine`,
`PruningState.attach_device_engine`) with the same fallback contract:
every engine failure serves THAT call from the host path; the first
failure logs one full traceback, later ones log at debug (a sick
device must not log-spam the serving path). This module is the ONE
place that policy lives — the seams configure the wording and the
exception types that must propagate, nothing else.

Lifecycle (classic three-state breaker, docs/robustness.md):

    CLOSED ──max_failures consecutive failures──► OPEN
      ▲                                             │ cooldown_s
      │ probe succeeds                              ▼
      └────────────────────────────────────── HALF-OPEN
                    probe fails: re-trip quietly ───┘ (one probe call)

While OPEN every call serves the fallback without touching the engine
(zero device round trips on the serving path). The first call after
the cooldown is a single probe: success closes the breaker and the
engine serves again; failure re-trips quietly (debug log) for another
cooldown. The seams therefore keep the engine ATTACHED across trips —
"re-attach" is the breaker closing again, never a new attach call, so
a transient device outage (driver restart, tunnel hiccup) heals
without operator intervention.
"""
from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)


class DeviceCircuitBreaker:
    def __init__(self, what: str, fallback: str, max_failures: int = 3,
                 reraise: tuple = (), cooldown_s: float = None,
                 clock=None):
        """what/fallback: log wording ("device proof engine" / "the
        host memo path"). reraise: exception types that are DOMAIN
        errors, not device faults (the host path would raise them too,
        or they must surface) — they propagate untouched and do not
        count against the device. cooldown_s: seconds the breaker
        stays OPEN before allowing a probe (default
        Config.BREAKER_COOLDOWN_S); clock: injectable monotonic clock
        for tests."""
        if cooldown_s is None:
            from plenum_tpu.common.config import Config
            cooldown_s = Config.BREAKER_COOLDOWN_S
        self.what = what
        self.fallback = fallback
        self.max_failures = max_failures
        self.reraise = tuple(reraise)
        self.cooldown_s = cooldown_s
        self._clock = clock or time.monotonic
        self.fail_count = 0
        # monotonic deadline of the current OPEN window; None = CLOSED
        self._open_until = None
        # observability: lifetime trip / successful-probe counts
        self.trips = 0
        self.recoveries = 0

    @property
    def open(self) -> bool:
        """True while the breaker serves everything from the fallback
        (OPEN or awaiting its HALF-OPEN probe)."""
        return self._open_until is not None

    # historical name: callers used to detach the engine on `tripped`;
    # the breaker now owns recovery, so this is just "open" — kept for
    # status dumps and tests that read breaker health
    tripped = open

    def probe_due(self) -> bool:
        """True when the next run() will probe the engine (cooldown
        elapsed on an open breaker)."""
        return self._open_until is not None \
            and self._clock() >= self._open_until

    def _trip(self, quiet: bool):
        self.trips += 1
        self._open_until = self._clock() + self.cooldown_s
        if quiet:
            logger.debug("%s probe failed; re-tripping for %.0fs",
                         self.what, self.cooldown_s, exc_info=True)
        else:
            logger.warning(
                "%s failed %d times; breaker OPEN for %.0fs (%s serves; "
                "one probe call after the cooldown)", self.what,
                self.fail_count, self.cooldown_s, self.fallback)

    def run(self, fn, label: str = ""):
        """Run one engine operation under the policy → (ok, result).
        ok False means serve this call from the host fallback. While
        OPEN, fn is not called at all; after the cooldown exactly one
        call becomes the recovery probe."""
        what = "{} {}".format(self.what, label).strip()
        if self._open_until is not None:
            if self._clock() < self._open_until:
                return False, None  # OPEN: quiet fallback, no device I/O
            # HALF-OPEN: this call is the single recovery probe
            try:
                out = fn()
            except self.reraise:
                raise
            except Exception:  # plenum-lint: disable=PT006 — this IS
                # the designed host-fallback boundary: ANY engine/device
                # failure must degrade to the host path, never crash
                self._trip(quiet=True)
                return False, None
            self._open_until = None
            self.fail_count = 0
            self.recoveries += 1
            logger.warning("%s recovered on probe; breaker CLOSED "
                           "(engine serves again)", what)
            return True, out
        try:
            out = fn()
        except self.reraise:
            raise
        except Exception:  # plenum-lint: disable=PT006 — this IS the
            # designed host-fallback boundary: ANY engine/device
            # failure must degrade to the host path, never crash
            self.fail_count += 1
            if self.fail_count >= self.max_failures:
                self._trip(quiet=False)
            elif self.fail_count == 1:
                logger.warning("%s failed; serving from %s", what,
                               self.fallback, exc_info=True)
            else:
                logger.debug("%s failed again (%d)", what,
                             self.fail_count, exc_info=True)
            return False, None
        self.fail_count = 0
        return True, out
