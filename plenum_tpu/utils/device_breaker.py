"""Shared circuit-breaker policy for attach-behind device engines.

Two subsystems attach an optional device engine behind a host
implementation (`CompactMerkleTree.attach_device_engine`,
`PruningState.attach_device_engine`) with the same fallback contract:
every engine failure serves THAT call from the host path; the first
failure logs one full traceback, later ones log at debug (a sick
device must not log-spam the serving path); after `max_failures`
CONSECUTIVE failures the breaker trips and the caller detaches the
engine for good. Success resets the count. This module is the ONE
place that policy lives — the seams configure the wording and the
exception types that must propagate, nothing else.
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


class DeviceCircuitBreaker:
    def __init__(self, what: str, fallback: str, max_failures: int = 3,
                 reraise: tuple = ()):
        """what/fallback: log wording ("device proof engine" / "the
        host memo path"). reraise: exception types that are DOMAIN
        errors, not device faults (the host path would raise them too,
        or they must surface) — they propagate untouched and do not
        count against the device."""
        self.what = what
        self.fallback = fallback
        self.max_failures = max_failures
        self.reraise = tuple(reraise)
        self.fail_count = 0

    @property
    def tripped(self) -> bool:
        """True once the caller should detach the engine."""
        return self.fail_count >= self.max_failures

    def run(self, fn, label: str = ""):
        """Run one engine operation under the policy → (ok, result).
        ok False means serve this call from the host fallback — and
        detach the engine if `tripped` flipped."""
        try:
            out = fn()
        except self.reraise:
            raise
        except Exception:  # plenum-lint: disable=PT006 — this IS the
            # designed host-fallback boundary: ANY engine/device
            # failure must degrade to the host path, never crash
            self.fail_count += 1
            what = "{} {}".format(self.what, label).strip()
            if self.tripped:
                logger.warning(
                    "%s failed %d times; detaching the engine (%s "
                    "serves from now on)", what, self.fail_count,
                    self.fallback)
            elif self.fail_count == 1:
                logger.warning("%s failed; serving from %s", what,
                               self.fallback, exc_info=True)
            else:
                logger.debug("%s failed again (%d)", what,
                             self.fail_count, exc_info=True)
            return False, None
        self.fail_count = 0
        return True, out
