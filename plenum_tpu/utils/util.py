"""Small shared helpers (reference: plenum/common/util.py)."""
import random
import string
import time
from collections import Counter
from typing import Any, Callable, Iterable, List, Optional, Sequence


def max_faulty(n: int) -> int:
    """f = ⌊(n-1)/3⌋ — max byzantine faults tolerated by an n-node pool
    (reference: plenum/common/util.py:220 getMaxFailures)."""
    return (n - 1) // 3


def check_if_more_than_f_same_items(items: Iterable[Any], f: int) -> Optional[Any]:
    """Return the item that occurs more than f times, if any (reference:
    plenum/common/util.py checkIfMoreThanFSameItems). Items are compared by a
    canonical JSON encoding (sorted keys at every nesting level) so dicts
    deserialized from different nodes with different key order still match."""
    import json

    def canon(i):
        try:
            return json.dumps(i, sort_keys=True, default=repr)
        except TypeError:
            return repr(i)

    keyed = [(canon(i), i) for i in items]
    counts = Counter(k for k, _ in keyed)
    if not counts:
        return None
    key, cnt = counts.most_common(1)[0]
    if cnt > f:
        for k, item in keyed:
            if k == key:
                return item
    return None


def random_string(size: int = 20, rng: Optional[random.Random] = None) -> str:
    rng = rng or random
    return ''.join(rng.choice(string.ascii_letters + string.digits)
                   for _ in range(size))


def hex_to_bytes(h: str) -> bytes:
    return bytes.fromhex(h)


def pop_keys(mapping: dict, cond: Callable[[Any], bool]) -> None:
    for k in [k for k in mapping if cond(k)]:
        mapping.pop(k)


def get_utc_epoch() -> int:
    """Integer UTC epoch seconds — consensus timestamps are ints (reference:
    plenum/common/util.py get_utc_epoch)."""
    return int(time.time())


def first(seq: Iterable[Any], default: Any = None) -> Any:
    for x in seq:
        return x
    return default


def update_named_tuple(nt, **kwargs):
    return nt._replace(**kwargs)


def min_containing_range(seqs: Sequence[int]) -> Optional[range]:
    if not seqs:
        return None
    return range(min(seqs), max(seqs) + 1)


def compare_3pc_keys(key1, key2) -> int:
    """Negative if key1 is after key2 (reference:
    plenum/common/util.py compare_3PC_keys). Keys are (view_no, pp_seq_no)."""
    if key1[0] == key2[0]:
        return key2[1] - key1[1]
    return key2[0] - key1[0]
