"""GC and process-memory observability.

Reference: plenum/common/gc_trackers.py — GcTimeTracker (:80) hooks
``gc.callbacks`` to record per-generation pause time and collected /
uncollectable object counts into the metrics collector; validator-info
surfaces process memory. Redesign: ONE process-wide gc callback fanning
out to weakly-referenced collectors (the reference registers one
callback per tracker and never removes it — with several nodes in one
process, dead nodes' callbacks would pile up forever), and RSS read
straight from /proc (no psutil in this image).
"""
from __future__ import annotations

import gc
import time
import weakref
from typing import Dict, Optional

from plenum_tpu.utils.metrics import MetricsCollector, MetricsName


class GcTimeTracker:
    """Process-wide GC pause/throughput tracker.

    ``attach(metrics)`` subscribes a collector to GC events; references
    are weak, so a collector (and the node owning it) dying is enough to
    unsubscribe. The single gc callback is installed lazily on first
    attach and then stays for the life of the process: the singleton's
    running totals feed validator-info's snapshot() even when no
    per-node collector is attached, and with an empty WeakSet the
    per-collection cost is a few counter updates.
    """

    _instance: Optional["GcTimeTracker"] = None

    def __init__(self):
        self._collectors: "weakref.WeakSet[MetricsCollector]" = \
            weakref.WeakSet()
        self._starts: Dict[int, float] = {}
        self._installed = False
        # running totals, cheap to snapshot for validator-info
        self.total_time = 0.0
        self.total_collected = 0
        self.total_uncollectable = 0
        self.collections = 0

    @classmethod
    def instance(cls) -> "GcTimeTracker":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def attach(self, metrics: MetricsCollector):
        self._collectors.add(metrics)
        if not self._installed:
            gc.callbacks.append(self._on_gc)
            self._installed = True

    def detach(self, metrics: MetricsCollector):
        self._collectors.discard(metrics)

    def _on_gc(self, action: str, info: dict):
        gen = info.get("generation", 0)
        if action == "start":
            self._starts[gen] = time.perf_counter()
            return
        start = self._starts.pop(gen, None)
        elapsed = (time.perf_counter() - start) if start is not None \
            else None
        collected = info.get("collected", 0)
        uncollectable = info.get("uncollectable", 0)
        self.collections += 1
        self.total_collected += collected
        self.total_uncollectable += uncollectable
        if elapsed is not None:
            self.total_time += elapsed
        if not self._collectors:
            return
        for m in list(self._collectors):
            if elapsed is not None:
                m.add_event(MetricsName.GC_GEN0_TIME + gen, elapsed)
            if collected:
                m.add_event(MetricsName.GC_COLLECTED_OBJECTS, collected)
            if uncollectable:
                m.add_event(MetricsName.GC_UNCOLLECTABLE_OBJECTS,
                            uncollectable)

    def snapshot(self) -> dict:
        counts = gc.get_count()
        return {
            "collections_observed": self.collections,
            "total_gc_time_s": round(self.total_time, 6),
            "total_collected_objects": self.total_collected,
            "total_uncollectable_objects": self.total_uncollectable,
            "current_counts": list(counts),
            "thresholds": list(gc.get_threshold()),
        }


def process_memory_info() -> dict:
    """RSS / peak-RSS / VM size for this process, in KiB. Linux /proc
    first (exact), resource.getrusage fallback (peak only)."""
    out: Dict[str, int] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(("VmRSS:", "VmHWM:", "VmSize:")):
                    key, val = line.split(":", 1)
                    out[{"VmRSS": "rss_kb", "VmHWM": "peak_rss_kb",
                         "VmSize": "vm_size_kb"}[key]] = \
                        int(val.strip().split()[0])
    except OSError:
        pass
    if "rss_kb" not in out or "peak_rss_kb" not in out:
        # sandboxed /proc (e.g. gVisor) may expose VmRSS without VmHWM
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)  # KiB on Linux
        out.setdefault("peak_rss_kb", max(ru.ru_maxrss,
                                          out.get("rss_kb", 0)))
        out.setdefault("rss_kb", ru.ru_maxrss)
    return out
