"""Recorder/replayer — capture a node's inputs, replay them later for
bit-identical state reproduction (determinism debugging).

Reference: plenum/recorder/recorder.py:13 (Recorder — timestamped
incoming/outgoing wire messages in KV) + replayer.py (re-feeding a
recorded node). Here the recording is (sim_time, kind, sender, wire
dict) JSONL; replay drives a FRESH node on a MockTimer, delivering each
input at its recorded time. The consensus core is single-threaded and
timer-driven, so identical inputs at identical times reproduce
identical ledger/state roots — asserted by the test harness.
"""
from __future__ import annotations

import base64
import json
import logging
from typing import Callable, List, Tuple

logger = logging.getLogger(__name__)

KIND_NODE_MSG = "node"      # peer consensus message
KIND_CLIENT_MSG = "client"  # client request dict

# JSONL cannot carry raw bytes (flat-wire FLAT_WIRE payloads are
# opaque byte strings): mark-and-base64 on dump, reversed on load, so
# a recorded flat envelope replays bit-identically
_BYTES_MARK = "__plenum_b64__"


def _to_jsonable(v):
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {_BYTES_MARK: base64.b64encode(bytes(v)).decode("ascii")}
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    return v


def _from_jsonable(v):
    if isinstance(v, dict):
        if set(v) == {_BYTES_MARK}:
            return base64.b64decode(v[_BYTES_MARK])
        return {k: _from_jsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_from_jsonable(x) for x in v]
    return v


class Recorder:
    def __init__(self, get_time: Callable[[], float]):
        self._get_time = get_time
        self.entries: List[Tuple[float, str, str, dict]] = []

    def add_node_msg(self, msg_dict: dict, frm: str):
        self.entries.append(
            (self._get_time(), KIND_NODE_MSG, frm, msg_dict))

    def add_client_msg(self, msg_dict: dict, client_id: str):
        self.entries.append(
            (self._get_time(), KIND_CLIENT_MSG, client_id, msg_dict))

    # ------------------------------------------------------ persistence

    def dump(self, path: str):
        with open(path, "w") as f:
            for t, kind, frm, payload in self.entries:
                f.write(json.dumps([t, kind, frm, _to_jsonable(payload)],
                                   sort_keys=True) + "\n")

    @classmethod
    def load(cls, path: str) -> "Recorder":
        rec = cls(get_time=lambda: 0.0)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    t, kind, frm, payload = json.loads(line)
                    rec.entries.append((t, kind, frm,
                                        _from_jsonable(payload)))
        return rec


def attach_recorder(node, recorder: Recorder) -> None:
    """Intercept a node's two input seams — peer messages entering its
    ExternalBus and client requests — recording the wire form of each
    before forwarding. Sends are NOT recorded: they are outputs, fully
    determined by the inputs."""
    bus = node.network
    orig_incoming = bus.process_incoming

    def recording_incoming(msg, frm):
        if hasattr(msg, "to_dict"):   # skip Connected/Disconnected marks
            recorder.add_node_msg(msg.to_dict(), frm)
        return orig_incoming(msg, frm)

    bus.process_incoming = recording_incoming

    orig_client = node.process_client_request

    def recording_client(msg_dict, client_id):
        recorder.add_client_msg(dict(msg_dict), client_id)
        return orig_client(msg_dict, client_id)

    node.process_client_request = recording_client


def replay(recorder: Recorder, node, timer,
           settle: float = 5.0, step: float = 0.05) -> None:
    """Feed a recording into a fresh `node` driven by MockTimer `timer`
    (which must start at or before the first entry's time). Each input
    is delivered at its recorded sim time; the node services between
    deliveries exactly as the live run did."""
    from plenum_tpu.common.messages.message_factory import (
        node_message_factory)

    def run_until(t: float):
        while timer.get_current_time() < t:
            node.service()
            remaining = t - timer.get_current_time()
            timer.run_for(min(step, remaining))
        node.service()

    for t, kind, frm, payload in sorted(recorder.entries,
                                        key=lambda e: e[0]):
        run_until(t)
        if kind == KIND_NODE_MSG:
            try:
                msg = node_message_factory.get_instance(**dict(payload))
            except Exception:
                # a dropped input makes the replay diverge — say so
                # loudly; silent skips defeat the tool's purpose
                logger.warning(
                    "replay: cannot reconstruct recorded message at "
                    "t=%s from %s (%r) — replay will diverge",
                    t, frm, payload, exc_info=True)
                continue
            node.network.process_incoming(msg, frm)
        elif kind == KIND_CLIENT_MSG:
            node.process_client_request(dict(payload), frm)
    # let in-flight work settle (same service/step cadence)
    end = timer.get_current_time() + settle
    run_until(end)
