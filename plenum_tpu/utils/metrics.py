"""Metrics collection: named events, accumulators, KV-backed storage.

Reference: plenum/common/metrics_collector.py (MetricsName :19,
MetricsCollector :331, KvStoreMetricsFormat :388,
KvStoreMetricsCollector :428, measure_time :348). Same model — cheap
in-memory accumulation per metric, periodic flush of compact records to
a KV store keyed by (timestamp, seq) — with a smaller, load-bearing
name set and a built-in reader that aggregates stats back out.
"""
from __future__ import annotations

import math
import struct
import time
from abc import ABC, abstractmethod
from collections import deque
from contextlib import contextmanager
from enum import IntEnum
from typing import Deque, Dict, Iterator, Optional, Tuple


class MetricsName(IntEnum):
    """~50 load-bearing ids wrapping every prod stage, the 3PC money
    path, transport, storage commits, and device dispatch — the subset
    of the reference's ~300-name MetricsName IntEnum
    (plenum/common/metrics_collector.py:19-326) that locates
    bottlenecks. scripts/metrics_stats renders the per-stage breakdown
    offline."""
    # ---- prod loop stages (reference node.py:1036-1076 wraps each)
    NODE_PROD_TIME = 1            # seconds per Node.service tick
    NODE_RX_TIME = 2              # nodestack recv+decode+route per tick
    CLIENT_RX_TIME = 3            # clientstack recv + intake dispatch
    TIMER_SERVICE_TIME = 4        # TimerService callbacks per tick
    TRANSPORT_FLUSH_TIME = 5      # outbox coalesce+seal+send per tick
    LIFECYCLE_TIME = 6            # reconnects/pings per tick
    # ---- ordering pipeline (per 3PC batch, not per request)
    ORDERED_BATCH_COMMITTED = 11  # txns committed per batch
    BACKUP_ORDERED = 13           # batches ordered by backup instances
    THREE_PC_BATCH_SIZE = 14      # digests per PrePrepare sent
    PP_CREATE_TIME = 15           # send_3pc_batch: pop+apply+build
    PP_PROCESS_TIME = 16          # process_preprepare incl. batch apply
    PREPARE_PROCESS_TIME = 17
    COMMIT_PROCESS_TIME = 18
    ORDER_TIME = 19               # _order: Ordered emit + BLS aggregate
    # ---- client intake / request pipeline
    CLIENT_AUTH_BATCH_SIZE = 20   # signatures per device dispatch
    CLIENT_AUTH_TIME = 21         # device-harvest (conclude) seconds
    REQUEST_INTAKE_TIME = 22      # process_client_request per request
    PROPAGATE_PROCESS_TIME = 24   # PROPAGATE receive path per message
    PROPAGATE_FLUSH_TIME = 25     # propagator outbox flush per tick
    BATCH_APPLY_TIME = 26         # executor.apply_batch (uncommitted)
    BATCH_COMMIT_TIME = 27        # executor.commit_batch (ledger+state)
    REPLY_TIME = 28               # reply construct + merkle audit path
    # ---- catchup
    CATCHUP_TXNS_RECEIVED = 30
    CATCHUP_TIME = 31             # start_catchup -> caught up, seconds
    # ---- view change
    VIEW_CHANGE_TIME = 40         # NeedViewChange -> NewView accepted
    INSTANCE_CHANGE_SENT = 41
    # ---- transport
    TRANSPORT_BATCH_SIZE = 50     # messages per outbox flush
    TRANSPORT_BYTES_SENT = 51     # wire bytes per sealed frame batch
    TRANSPORT_BYTES_RECV = 52
    TRANSPORT_MSGS_RECV = 53
    WIRE_ENCODE_TIME = 54         # serialize+seal per outbox flush
    WIRE_DECODE_TIME = 55         # open+decode per service() call
    # ---- garbage collector (reference gc_trackers.py GcTimeTracker):
    # the three *_TIME names MUST stay consecutive — the tracker
    # indexes them as GC_GEN0_TIME + generation
    GC_GEN0_TIME = 60             # seconds paused in a gen-0 collection
    GC_GEN1_TIME = 61
    GC_GEN2_TIME = 62
    GC_COLLECTED_OBJECTS = 63     # objects freed per collection
    GC_UNCOLLECTABLE_OBJECTS = 64
    # ---- device dispatch + crypto
    DEVICE_DISPATCH_TIME = 70     # host-side launch cost per dispatch
    BLS_AGGREGATE_TIME = 72       # process_order share aggregation
    BLS_VALIDATE_TIME = 73        # validate_commit pairing check
    # ---- storage commits (inside BATCH_COMMIT_TIME)
    LEDGER_COMMIT_TIME = 75       # merkle append + txn log write
    STATE_COMMIT_TIME = 76        # MPT commit to new root
    AUDIT_BATCH_TIME = 77         # audit txn build + append
    # ---- monitor observations
    MASTER_THROUGHPUT = 80
    MASTER_AVG_LATENCY = 81
    MONITOR_CHECK_TIME = 82


class ValueAccumulator:
    """count/sum/sumsq/min/max running stats for one metric between
    flushes. Keeping the SUM OF SQUARES (not a running variance) is
    what makes `merge` exact: variances don't add across windows, but
    (count, sum, sumsq) triples do — merged-then-read stddev equals
    recording everything into one accumulator. `sumsq` is None for
    records decoded from the pre-variance on-disk format (their
    squares are unrecoverable), and merging any such record poisons
    the merged stddev to None rather than inventing a number."""

    __slots__ = ("count", "sum", "min", "max", "sumsq")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.sumsq: Optional[float] = 0.0

    def add(self, value: float):
        self.count += 1
        self.sum += value
        if self.sumsq is not None:
            self.sumsq += value * value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    @property
    def avg(self) -> Optional[float]:
        return (self.sum / self.count) if self.count else None

    @property
    def stddev(self) -> Optional[float]:
        """Population standard deviation; None when empty or when any
        merged-in record predates the sumsq format."""
        if not self.count or self.sumsq is None:
            return None
        mean = self.sum / self.count
        var = self.sumsq / self.count - mean * mean
        return math.sqrt(var) if var > 0.0 else 0.0

    def merge(self, other: "ValueAccumulator"):
        if (self.count and self.sumsq is None) or \
                (other.count and other.sumsq is None):
            self.sumsq = None
        else:
            self.sumsq = (self.sumsq or 0.0) + (other.sumsq or 0.0)
        self.count += other.count
        self.sum += other.sum
        for v in (other.min, other.max):
            if v is None:
                continue
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)


class MetricsCollector(ABC):
    """add_event accumulates in memory; flush_accumulated persists."""

    def __init__(self, get_time=time.time):
        self._get_time = get_time
        self._acc: Dict[int, ValueAccumulator] = {}

    def add_event(self, name: MetricsName, value: float):
        acc = self._acc.get(int(name))
        if acc is None:
            acc = self._acc[int(name)] = ValueAccumulator()
        acc.add(float(value))

    def flush_accumulated(self):
        ts = self._get_time()
        for name, acc in self._acc.items():
            self._store(ts, name, acc)
        self._acc.clear()

    @abstractmethod
    def _store(self, ts: float, name: int, acc: ValueAccumulator): ...

    @contextmanager
    def measure_time(self, name: MetricsName):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add_event(name, time.perf_counter() - start)


class NullMetricsCollector(MetricsCollector):
    def add_event(self, name, value):
        pass

    def _store(self, ts, name, acc):
        pass


_RECORD = struct.Struct(">dHIdddd")  # ts, name, count, sum, min, max, sumsq
# the pre-variance record layout (no sumsq); still decoded on read so
# stores written by earlier builds keep parsing — their stddev reads
# as unknown (None), never as a fabricated 0
_RECORD_V1 = struct.Struct(">dHIddd")  # ts, name, count, sum, min, max


class KvStoreMetricsCollector(MetricsCollector):
    """Flushes accumulator records to a KeyValueStorage. Key = 8-byte
    big-endian microsecond timestamp + 4-byte seq (sortable, unique);
    value = packed (ts, name, count, sum, min, max, sumsq). Records in
    the old sumsq-less layout are decoded transparently."""

    def __init__(self, storage, get_time=time.time,
                 max_records: Optional[int] = 100_000):
        """max_records=None disables retention entirely — the mode for
        READ-ONLY consumers (scripts/metrics_stats): a reporting tool
        must never trim a live node's history on open."""
        super().__init__(get_time)
        self._storage = storage
        self._seq = 0
        self._max_records = max_records
        # insertion order (keys sort by flush timestamp), for retention
        self._record_keys: Deque[bytes] = deque()
        # running per-metric totals so summary() is O(metrics), not
        # O(stored history); BOTH the totals and the retention index are
        # seeded from whatever is already on disk — an unseeded index
        # would make the max_records cap count only this run's records,
        # letting prior-run history survive every restart untrimmed
        self._totals: Dict[int, ValueAccumulator] = {}
        for key, _ts, name, acc in self._iter_records():
            self._totals.setdefault(name, ValueAccumulator()).merge(acc)
            self._record_keys.append(key)
        self._trim()   # cap may have shrunk since the records landed

    def _store(self, ts: float, name: int, acc: ValueAccumulator):
        key = struct.pack(">QI", int(ts * 1e6), self._seq)
        self._seq = (self._seq + 1) & 0xFFFFFFFF
        value = _RECORD.pack(ts, name, acc.count, acc.sum,
                             acc.min if acc.min is not None else 0.0,
                             acc.max if acc.max is not None else 0.0,
                             acc.sumsq if acc.sumsq is not None
                             else float("nan"))
        self._storage.put(key, value)
        self._totals.setdefault(name, ValueAccumulator()).merge(acc)
        # retention: drop oldest records past the cap (totals keep the
        # all-time aggregate; only the per-flush history is trimmed)
        self._record_keys.append(key)
        self._trim()

    def _trim(self):
        if self._max_records is None:
            return
        while len(self._record_keys) > self._max_records:
            old = self._record_keys.popleft()
            try:
                self._storage.remove(old)
            except Exception:
                # a store that refuses removal keeps the record AND its
                # index entry — retrying next flush beats losing track
                self._record_keys.appendleft(old)
                break

    def _iter_records(self) -> Iterator[
            Tuple[bytes, float, int, ValueAccumulator]]:
        """Decode every stored record — the ONE place that understands
        the on-disk format (restart seeding and events() both ride it)."""
        for key, value in self._storage.iterator():
            if len(value) == _RECORD.size:
                ts, name, count, total, mn, mx, sumsq = \
                    _RECORD.unpack(value)
                if sumsq != sumsq:      # NaN sentinel → unknown
                    sumsq = None
            elif len(value) == _RECORD_V1.size:
                # old 4-tuple (count/sum/min/max) record: parses fine,
                # stddev unknown
                ts, name, count, total, mn, mx = _RECORD_V1.unpack(value)
                sumsq = None
            else:
                continue
            acc = ValueAccumulator()
            acc.count, acc.sum = count, total
            acc.min, acc.max = mn, mx
            acc.sumsq = sumsq
            yield bytes(key), ts, name, acc

    def events(self) -> Iterator[Tuple[float, int, ValueAccumulator]]:
        for _key, ts, name, acc in self._iter_records():
            yield ts, name, acc

    def summary(self) -> Dict[str, dict]:
        """All-time per-metric stats (incl. unflushed) from the running
        totals — O(number of metrics), never walks stored history."""
        totals: Dict[int, ValueAccumulator] = {}
        for name, acc in self._totals.items():
            merged = ValueAccumulator()
            merged.merge(acc)
            totals[name] = merged
        for name, acc in self._acc.items():
            totals.setdefault(name, ValueAccumulator()).merge(acc)
        out = {}
        for name, acc in sorted(totals.items()):
            try:
                label = MetricsName(name).name
            except ValueError:
                label = str(name)
            out[label] = {"count": acc.count, "sum": acc.sum,
                          "avg": acc.avg, "min": acc.min, "max": acc.max,
                          "stddev": acc.stddev}
        return out


