"""Call spying for tests (reference @spyable/SpyLog,
plenum/test/testable.py:110): wrap a bound method so every call is
recorded with args and result, assert "the node ordered K batches" /
"catchup was triggered once" style facts without touching production
code.

Caveat: bus subscriptions capture bound methods at construction, so a
spy attached afterwards does NOT see bus-routed deliveries — observe
those at the wire with sim_network.Tap instead. spy_on works for
methods invoked through attribute lookup (node.service, executor
hooks, storage calls, ...).
"""
from __future__ import annotations

from typing import Any, List, NamedTuple


class SpyCall(NamedTuple):
    args: tuple
    kwargs: dict
    result: Any
    error: Any


class SpyLog(List[SpyCall]):
    def count(self) -> int:
        return len(self)

    def last(self) -> SpyCall:
        return self[-1]

    def results(self) -> list:
        return [c.result for c in self]


def spy_on(obj, method_name: str) -> SpyLog:
    """Replace obj.method with a recording wrapper; returns the log.
    Restore with unspy(obj, method_name)."""
    original = getattr(obj, method_name)
    log = SpyLog()

    def wrapper(*args, **kwargs):
        try:
            result = original(*args, **kwargs)
        except Exception as e:
            log.append(SpyCall(args, kwargs, None, e))
            raise
        log.append(SpyCall(args, kwargs, result, None))
        return result

    wrapper._spy_original = original
    wrapper._spy_log = log
    setattr(obj, method_name, wrapper)
    return log


def unspy(obj, method_name: str) -> None:
    wrapper = getattr(obj, method_name)
    original = getattr(wrapper, "_spy_original", None)
    if original is not None:
        setattr(obj, method_name, original)
