"""Deterministic simulated network: delivers ExternalBus sends through a
chain of processors (drop / delay / stash) on a MockTimer.

Reference: plenum/test/simulation/sim_network.py:98 (SimNetwork),
:14-40 (Discard/Deliver/Stash processors). Seeded by DefaultSimRandom so
partition/latency fuzzing of view change + ordering is replayable.
"""
import heapq
import logging
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from plenum_tpu.runtime.bus import ExternalBus
from plenum_tpu.runtime.sim_random import SimRandom, DefaultSimRandom
from plenum_tpu.testing.mock_timer import MockTimer

logger = logging.getLogger(__name__)


class PendingMessage(NamedTuple):
    message: Any
    frm: str
    dst: str


class Processor:
    """Returns True if it consumed the message (stops the chain)."""

    def process(self, msg: PendingMessage) -> bool:
        raise NotImplementedError

    def _matches(self, msg: PendingMessage, frm=None, dst=None,
                 message_types=None) -> bool:
        if frm is not None and msg.frm not in frm:
            return False
        if dst is not None and msg.dst not in dst:
            return False
        if message_types is not None and not isinstance(msg.message,
                                                        tuple(message_types)):
            return False
        return True


class Discard(Processor):
    def __init__(self, random: SimRandom, probability: float = 1.0,
                 frm=None, dst=None, message_types=None):
        self._random = random
        self._probability = probability
        self._filters = dict(frm=frm, dst=dst, message_types=message_types)

    def process(self, msg: PendingMessage) -> bool:
        if not self._matches(msg, **self._filters):
            return False
        return self._random.float(0.0, 1.0) < self._probability


class Stash(Processor):
    def __init__(self, frm=None, dst=None, message_types=None):
        self._filters = dict(frm=frm, dst=dst, message_types=message_types)
        self.stashed: List[PendingMessage] = []

    def process(self, msg: PendingMessage) -> bool:
        if self._matches(msg, **self._filters):
            self.stashed.append(msg)
            return True
        return False

    def pop_all(self) -> List[PendingMessage]:
        msgs, self.stashed = self.stashed, []
        return msgs


class Tap(Processor):
    """Record matching messages WITHOUT consuming them (wire-level spy:
    the bus subscriptions capture bound methods at construction, so
    attribute-level spies can't see handler traffic — observe the wire
    instead)."""

    def __init__(self, frm=None, dst=None, message_types=None):
        self._filters = dict(frm=frm, dst=dst, message_types=message_types)
        self.seen: List[PendingMessage] = []

    def process(self, msg: PendingMessage) -> bool:
        if self._matches(msg, **self._filters):
            self.seen.append(msg)
        return False


class Delay(Processor):
    """Deliver matching messages `extra` seconds late (reference
    delayer combinators, plenum/test/delayers.py — ppDelay/cDelay/
    icDelay are this with a message_types filter). Each delayed message
    still draws its own base latency, so two equally delayed messages
    may reorder exactly like two undelayed ones; only identical
    deadlines keep FIFO (the seq tie-break)."""

    def __init__(self, network: "SimNetwork", extra: float,
                 frm=None, dst=None, message_types=None):
        self._network = network
        self.extra = extra
        self._filters = dict(frm=frm, dst=dst, message_types=message_types)

    def process(self, msg: PendingMessage) -> bool:
        if not self._matches(msg, **self._filters):
            return False
        self._network._schedule_delivery(msg, extra=self.extra)
        return True


def _unwrap_envelope(message):
    """Constituent typed messages of a coalesced wire envelope
    (THREE_PC_BATCH or a flat-wire FLAT_WIRE payload), or None when
    `message` is not one. Local imports: the sim network must stay
    importable without the full message schema module loaded first."""
    from plenum_tpu.common.messages.node_messages import (
        FlatBatch, ThreePCBatch)
    if isinstance(message, ThreePCBatch):
        return list(message.messages)
    if isinstance(message, FlatBatch):
        from plenum_tpu.common.serializers import flat_wire
        # malformed / all-entries-invalid envelopes deliver WHOLE so
        # the receiving node does the judging, exactly like real
        # transport — the policy is single-sourced next to the codec
        return flat_wire.unwrap_for_tap(message.payload)
    return None


class SimNetwork:
    def __init__(self, timer: MockTimer, random: Optional[SimRandom] = None,
                 serialize_deserialize: Callable[[Any], Any] = None,
                 min_latency: float = 0.01, max_latency: float = 0.5):
        self._timer = timer
        self._random = random or DefaultSimRandom()
        self._min_latency = min_latency
        self._max_latency = max_latency
        self._serde = serialize_deserialize
        self._buses: Dict[str, ExternalBus] = {}
        self._down: set = set()
        self.processors: List[Processor] = []
        self.sent_count = 0
        # in-flight messages keyed by absolute deadline; ONE timer event
        # (the pump) drains everything due instead of one closure+event
        # per message — at n nodes each request generates O(n^2) sends
        # and the per-event cost dominated the 25-node sim. Latency
        # draws and delivery times are unchanged, so seeded runs are
        # bit-identical.
        self._pending: List = []         # [deadline, seq, PendingMessage]
        self._seq = 0
        # generation-tagged arming: exactly one LIVE pump; superseded
        # ones return immediately (re-arming blindly made every stale
        # pump spawn another — an event storm at 25 nodes)
        self._pump_gen = 0
        self._pump_deadline: Optional[float] = None

    def create_peer(self, name: str, send_handler=None) -> ExternalBus:
        """send_handler overrides the simulated transport for this peer
        (reference sim_network.py:116) — used by tests to spy on sends."""
        if name in self._buses:
            raise ValueError("Peer {} already exists".format(name))
        bus = ExternalBus(send_handler=send_handler or
                          self._make_send_handler(name))
        self._buses[name] = bus
        # downed peers are NOT connected to the newcomer (a node joining
        # while the primary is dead must see it as disconnected)
        for peer, other in self._buses.items():
            if peer != name and peer not in self._down:
                other.update_connecteds(other.connecteds | {name})
        bus.update_connecteds(set(p for p in self._buses
                                  if p != name and p not in self._down))
        return bus

    def remove_peer(self, name: str):
        """Forget a peer entirely so a restarted node can create_peer
        under the same name (node restart in tests)."""
        self.disconnect(name)
        self._buses.pop(name, None)
        self._down.discard(name)

    def disconnect(self, name: str):
        """Take a peer down: its traffic stops both ways and every other
        peer sees an ExternalBus.Disconnected event (reference
        onConnsChanged node.py:1169 trigger side)."""
        self._down.add(name)
        for peer, bus in self._buses.items():
            if peer != name:
                bus.update_connecteds(bus.connecteds - {name})
        me = self._buses.get(name)
        if me is not None:
            me.update_connecteds(set())

    def reconnect(self, name: str):
        """Bring a downed peer back; still-up peers see Connected events
        (peers that are themselves down stay fully isolated)."""
        self._down.discard(name)
        for peer, bus in self._buses.items():
            if peer != name and peer not in self._down:
                bus.update_connecteds(bus.connecteds | {name})
        me = self._buses.get(name)
        if me is not None:
            me.update_connecteds(
                set(p for p in self._buses if p != name and
                    p not in self._down))

    def add_processor(self, processor: Processor):
        self.processors.append(processor)

    def remove_processor(self, processor: Processor):
        self.processors.remove(processor)

    def reset_filters(self):
        self.processors = []

    def deliver_stashed(self, stash: Stash):
        for msg in stash.pop_all():
            self._schedule_delivery(msg)

    def _make_send_handler(self, frm: str):
        def handle(message, dst=None):
            if dst is None:
                dsts = [p for p in self._buses if p != frm]
            elif isinstance(dst, str):
                dsts = [dst]
            else:
                dsts = list(dst)
            # fault injection needs per-message wire granularity: while
            # processors are installed, coalesced envelopes (typed
            # THREE_PC_BATCH and flat FLAT_WIRE alike) unwrap into
            # their constituent votes so drop/delay/stash/tap filters
            # (and per-message latency draws) behave exactly as on the
            # legacy per-message wire. Uninstrumented pools keep the
            # envelope whole — one delivery per peer per flush.
            messages = [message]
            if self.processors:
                inner = _unwrap_envelope(message)
                if inner is not None:
                    messages = inner
            for d in dsts:
                if d == frm or d in self._down or frm in self._down:
                    continue
                for entry in messages:
                    self.sent_count += 1
                    msg = PendingMessage(entry, frm, d)
                    if self.processors and any(p.process(msg)
                                               for p in self.processors):
                        continue
                    self._schedule_delivery(msg)
        return handle

    def _schedule_delivery(self, msg: PendingMessage, extra: float = 0.0):
        delay = self._random.float(self._min_latency, self._max_latency) \
            + extra
        deadline = self._timer.get_current_time() + delay
        self._seq += 1
        heapq.heappush(self._pending, (deadline, self._seq, msg))
        if self._pump_deadline is None or deadline < self._pump_deadline:
            self._arm(deadline)

    def _arm(self, deadline: float):
        self._pump_gen += 1
        gen = self._pump_gen
        self._pump_deadline = deadline
        delay = max(0.0, deadline - self._timer.get_current_time())
        self._timer.schedule(delay, lambda: self._pump(gen))

    def _pump(self, gen: int):
        """Deliver every due in-flight message, then re-arm for the next
        deadline. Only the latest-armed pump runs; superseded ones are
        no-ops."""
        if gen != self._pump_gen:
            return
        self._pump_deadline = None
        now = self._timer.get_current_time()
        pending = self._pending
        while pending and pending[0][0] <= now:
            _, _, msg = heapq.heappop(pending)
            self._deliver(msg)
        if pending and (self._pump_deadline is None
                        or pending[0][0] < self._pump_deadline):
            self._arm(pending[0][0])

    def _deliver(self, msg: PendingMessage):
        bus = self._buses.get(msg.dst)
        if bus is None or msg.dst in self._down or msg.frm in self._down:
            return
        payload = msg.message
        if self._serde is not None:
            payload = self._serde(payload)
        bus.process_incoming(payload, msg.frm)
