"""Manually advanced clock driving QueueTimer callbacks — the backbone of
deterministic consensus tests (reference: plenum/test/helper.py:1369 MockTimer).
"""
from typing import Callable

from plenum_tpu.runtime.timer import QueueTimer


class MockTimer(QueueTimer):
    def __init__(self, start_time: float = 0.0):
        self._current_time = start_time
        super().__init__(get_current_time=lambda: self._current_time)

    def set_time(self, value: float):
        """Advance to `value`, firing every due event in timestamp order.
        Events scheduled while firing are honored if they fall before value."""
        while True:
            entry = self._peek()
            if entry is None or entry[0] > value:
                break
            self._pop()
            self._current_time = max(self._current_time, entry[0])
            entry[2]()
        self._current_time = max(self._current_time, value)

    def sleep(self, seconds: float):
        self.set_time(self._current_time + seconds)

    def advance(self):
        """Fire just the next scheduled event (if any)."""
        entry = self._pop()
        if entry is not None:
            self._current_time = max(self._current_time, entry[0])
            entry[2]()

    def advance_until(self, value: float):
        while True:
            entry = self._peek()
            if entry is None or entry[0] > value:
                break
            self.advance()

    def run_for(self, seconds: float):
        self.set_time(self._current_time + seconds)

    def wait_for(self, condition: Callable[[], bool], timeout: float = None,
                 max_iterations: int = 10000):
        """Advance through scheduled events until condition() holds.
        Raises TimeoutError if events run out or timeout exceeded."""
        deadline = (self._current_time + timeout) if timeout is not None else None
        for _ in range(max_iterations):
            if condition():
                return
            entry = self._peek()
            if entry is None:
                raise TimeoutError(
                    "Condition not reached and no more timer events at t={}"
                    .format(self._current_time))
            if deadline is not None and entry[0] > deadline:
                raise TimeoutError(
                    "Condition not reached before t={}".format(deadline))
            self.advance()
        raise TimeoutError("Condition not reached in {} timer events"
                           .format(max_iterations))
