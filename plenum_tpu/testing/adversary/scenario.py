"""Scenario runner: drives a sim pool tick by tick, evaluating the
safety invariant checkers after EVERY tick, with bounded-window
liveness assertions for the recovery phase of a fault plan.

When a safety invariant fails and the pool is traced (Config
TRACING_ENABLED), the runner automatically dumps the merged pool
flight-recorder timeline (observability/) next to the failure — the
ring buffers hold exactly the window leading up to the violation —
plus the joined JOURNEY report (observability/journey.py): per-request
causal records, the per-link clock model, and the equivocation
evidence chain (every (viewNo:ppSeqNo) slot where nodes processed
conflicting PRE-PREPARE digests, with who observed which digest from
whom, and when). A fork dump therefore names the culprit slot and
sender without rerunning anything.
Override the directory with PLENUM_TPU_TRACE_DIR.

Soak mode (docs/robustness.md): `soak(rounds, fault, ...)` repeats
inject → measure recovery (sim seconds) → heal → settle, gating each
round's latency through `check_slo` — an SLO violation dumps the same
merged timeline with the measured latency and threshold embedded in
the filename and assertion text."""
from __future__ import annotations

import logging
import os
import tempfile
from typing import Callable, List, Optional

from plenum_tpu.runtime.sanitizer import RegionViolation
from plenum_tpu.testing.adversary.invariants import InvariantChecker

logger = logging.getLogger(__name__)

# process-wide dump counter: two failing scenarios in one process (e.g.
# one pytest run) must not overwrite each other's timelines
_dump_seq = [0]


class LivenessViolation(AssertionError):
    """The pool failed to make progress inside the bounded window."""


class SLOViolation(AssertionError):
    """A recovery-latency SLO was exceeded. The assertion text (and the
    auto-dumped flight-recorder filename) embeds the measured latency
    and the threshold, so a soak failure is triageable from the
    artifact alone — no need to rerun to learn how bad it was."""


class Scenario:
    def __init__(self, timer, nodes, adversary=None,
                 honest: Optional[List[str]] = None,
                 checker: Optional[InvariantChecker] = None,
                 step: float = 0.05):
        self.timer = timer
        self.nodes = list(nodes)
        self.adversary = adversary
        if honest is None:
            corrupted = set(adversary.adversaries) if adversary else set()
            honest = [n.name for n in nodes if n.name not in corrupted]
        self.honest_names = list(honest)
        self.checker = checker or InvariantChecker(nodes, honest)
        self.step = step
        if adversary is not None and not adversary.pool_names():
            adversary.set_pool(nodes)

    # ------------------------------------------------------------- drive

    @property
    def honest(self) -> List:
        return [n for n in self.nodes if n.name in self.honest_names]

    def run(self, seconds: float) -> "Scenario":
        """Pump the pool for `seconds` of sim time, checking every
        safety invariant after every tick."""
        end = self.timer.get_current_time() + seconds
        while self.timer.get_current_time() < end:
            self._tick()
        return self

    def run_until(self, condition: Callable[[], bool], timeout: float,
                  desc: str) -> "Scenario":
        """Pump until condition() holds; LivenessViolation on timeout —
        the bounded-window liveness assertion."""
        deadline = self.timer.get_current_time() + timeout
        while not condition():
            if self.timer.get_current_time() >= deadline:
                raise LivenessViolation(
                    "liveness: {} not reached within {}s (t={})".format(
                        desc, timeout, self.timer.get_current_time()))
            self._tick()
        return self

    def _tick(self) -> None:
        try:
            # service inside the try: an ownership-sanitizer violation
            # raised mid-service gets the same pool-wide dump treatment
            # as a failed safety invariant
            for node in self.nodes:
                node.service()
            self.timer.run_for(self.step)
            self.checker.check()
        except (AssertionError, RegionViolation) as e:
            path = self.dump_trace()
            if path:
                logger.error("safety invariant failed — flight-recorder "
                             "timeline dumped to %s (load in "
                             "ui.perfetto.dev)", path)
                if e.args and isinstance(e.args[0], str):
                    e.args = ("%s [flight recorder: %s]"
                              % (e.args[0], path),) + e.args[1:]
            jpath, equivs = self.dump_journey()
            if jpath:
                logger.error("journey + equivocation evidence dumped "
                             "to %s (%d equivocating slot(s))",
                             jpath, equivs)
                if e.args and isinstance(e.args[0], str):
                    tag = " [journeys: %s" % jpath
                    if equivs:
                        tag += "; EQUIVOCATION in %d slot(s)" % equivs
                    e.args = (e.args[0] + tag + "]",) + e.args[1:]
            raise

    def dump_trace(self, path: Optional[str] = None,
                   tag: str = "invariant_failure") -> Optional[str]:
        """Merge every traced node's ring buffer into one pool-wide
        Chrome trace-event file. → path, or None when no node has
        tracing enabled. `tag` lands in the generated filename so an
        artifact directory full of dumps stays self-describing (SLO
        dumps embed the measured latency and threshold there)."""
        from plenum_tpu.observability.export import (
            export_chrome_trace, pool_tracers)
        tracers = [t for t in pool_tracers(self.nodes)
                   if getattr(t, "enabled", False)]
        if not tracers:
            return None
        if path is None:
            out_dir = os.environ.get("PLENUM_TPU_TRACE_DIR") \
                or tempfile.gettempdir()
            _dump_seq[0] += 1
            path = os.path.join(
                out_dir, "%s_trace_%d_%d.json"
                % (tag, os.getpid(), _dump_seq[0]))
        try:
            return export_chrome_trace(tracers, path)
        except OSError:
            logger.warning("could not write flight-recorder trace to %s",
                           path, exc_info=True)
            return None

    def dump_journey(self, path: Optional[str] = None,
                     tag: str = "invariant_failure"
                     ) -> tuple:
        """Join every traced node's buffer into the journey report —
        per-request causal records plus the equivocation evidence
        chain — and write it next to the timeline dump. → (path,
        equivocating_slot_count), or (None, 0) when nothing is traced
        or the write fails. The report is the triage half of a fork
        dump: the timeline shows WHERE time went, the evidence chain
        shows WHO sent conflicting digests for WHICH slot, and WHEN
        each honest node saw them."""
        import json

        from plenum_tpu.observability import journey
        from plenum_tpu.observability.export import pool_tracers
        tracers = [t for t in pool_tracers(self.nodes)
                   if getattr(t, "enabled", False)]
        if not tracers:
            return None, 0
        report = journey.journeys_from_tracers(tracers)
        doc = journey.to_json(report)
        doc["causal_violations"] = journey.causal_violations(report)
        if path is None:
            out_dir = os.environ.get("PLENUM_TPU_TRACE_DIR") \
                or tempfile.gettempdir()
            _dump_seq[0] += 1
            path = os.path.join(
                out_dir, "%s_journeys_%d_%d.json"
                % (tag, os.getpid(), _dump_seq[0]))
        try:
            with open(path, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
        except (OSError, TypeError, ValueError):
            logger.warning("could not write journey report to %s",
                           path, exc_info=True)
            return None, 0
        return path, len(doc.get("equivocations") or ())

    # ------------------------------------------------- recovery SLOs

    def measure(self, condition: Callable[[], bool], within: float,
                desc: str) -> float:
        """Pump until condition() holds → elapsed SIM seconds (the
        recovery-latency measurement primitive: deterministic under
        MockTimer, independent of host load)."""
        t0 = self.timer.get_current_time()
        self.run_until(condition, within, desc)
        return self.timer.get_current_time() - t0

    def check_slo(self, name: str, measured_s: float,
                  threshold_s: float) -> float:
        """Gate a measured recovery latency against its SLO. On
        violation the merged flight-recorder timeline is auto-dumped
        with the measured latency AND the threshold embedded in the
        filename, and the raised assertion text carries both plus the
        dump path — the failure artifact alone tells the whole story."""
        if measured_s <= threshold_s:
            return measured_s
        tag = "slo_%s_%.2fs_gt_%.2fs" % (name, measured_s, threshold_s)
        path = self.dump_trace(tag=tag.replace("/", "_"))
        text = ("recovery SLO '%s' violated: measured %.2fs > "
                "threshold %.2fs (sim time)" % (name, measured_s,
                                                threshold_s))
        if path:
            logger.error("%s — flight-recorder timeline dumped to %s "
                         "(load in ui.perfetto.dev)", text, path)
            text += " [flight recorder: %s]" % path
        raise SLOViolation(text)

    # ------------------------------------------------------ soak mode

    def soak(self, rounds: int, fault: Callable[[int], tuple],
             settle: float = 5.0, within: float = 60.0,
             slo: Optional[float] = None,
             slo_name: str = "recovery") -> List[dict]:
        """Repeated fault rounds with per-tick safety invariants and
        per-round recovery-latency measurement — the long-run shape
        where real RBFT deployments break (faults landing on a pool
        still digesting the previous fault's recovery).

        fault(round_idx) → (desc, recovered_condition, heal_fn|None):
        inject the fault before returning; `recovered_condition` is
        pumped under invariant checks until true (LivenessViolation
        after `within` sim seconds); heal_fn (if any) runs after
        recovery; then the pool settles for `settle` sim seconds before
        the next round. With `slo` set, every round's recovery latency
        is gated through check_slo (auto-dumping timelines on
        violation). → per-round records [{round, fault, recovery_s}]."""
        results: List[dict] = []
        for r in range(rounds):
            desc, recovered, heal = fault(r)
            latency = self.measure(
                recovered, within, "round %d: %s" % (r, desc))
            if heal is not None:
                heal()
            if settle:
                self.run(settle)
            results.append({"round": r, "fault": desc,
                            "recovery_s": round(latency, 3)})
            if slo is not None:
                self.check_slo("%s_round%d" % (slo_name, r), latency,
                               slo)
        return results

    # ------------------------------------------------- liveness helpers

    def await_ordering_resumes(self, extra_batches: int = 1,
                               within: float = 30.0) -> "Scenario":
        """Honest nodes must each order `extra_batches` more batches
        within the window (the fault is over / absorbed)."""
        base = {n.name: _last_seq(n) for n in self.honest}

        def resumed():
            return all(_last_seq(n) >= base[n.name] + extra_batches
                       for n in self.honest)

        return self.run_until(
            resumed, within,
            "+{} ordered batches on every honest node".format(
                extra_batches))

    def await_view_change(self, min_view: int = 1,
                          within: float = 60.0) -> "Scenario":
        """Every honest node must complete a view change to at least
        `min_view` (adversarial-primary recovery)."""

        def done():
            return all(
                _replica(n).view_no >= min_view
                and not _replica(n).data.waiting_for_new_view
                for n in self.honest)

        return self.run_until(
            done, within, "view change to >= {}".format(min_view))

    def await_catchup_done(self, node, within: float = 60.0) -> "Scenario":
        """The node's leecher must finish syncing every ledger within
        the window (catchup-completion liveness)."""
        return self.run_until(
            lambda: not node.leecher.in_progress, within,
            "catchup completes on {}".format(node.name))


def _replica(node):
    return getattr(node, "replica", node)


def _last_seq(node) -> int:
    return _replica(node).last_ordered[1]
