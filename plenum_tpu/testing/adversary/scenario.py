"""Scenario runner: drives a sim pool tick by tick, evaluating the
safety invariant checkers after EVERY tick, with bounded-window
liveness assertions for the recovery phase of a fault plan.

When a safety invariant fails and the pool is traced (Config
TRACING_ENABLED), the runner automatically dumps the merged pool
flight-recorder timeline (observability/) next to the failure — the
ring buffers hold exactly the window leading up to the violation.
Override the directory with PLENUM_TPU_TRACE_DIR."""
from __future__ import annotations

import logging
import os
import tempfile
from typing import Callable, List, Optional

from plenum_tpu.testing.adversary.invariants import InvariantChecker

logger = logging.getLogger(__name__)

# process-wide dump counter: two failing scenarios in one process (e.g.
# one pytest run) must not overwrite each other's timelines
_dump_seq = [0]


class LivenessViolation(AssertionError):
    """The pool failed to make progress inside the bounded window."""


class Scenario:
    def __init__(self, timer, nodes, adversary=None,
                 honest: Optional[List[str]] = None,
                 checker: Optional[InvariantChecker] = None,
                 step: float = 0.05):
        self.timer = timer
        self.nodes = list(nodes)
        self.adversary = adversary
        if honest is None:
            corrupted = set(adversary.adversaries) if adversary else set()
            honest = [n.name for n in nodes if n.name not in corrupted]
        self.honest_names = list(honest)
        self.checker = checker or InvariantChecker(nodes, honest)
        self.step = step
        if adversary is not None and not adversary.pool_names():
            adversary.set_pool(nodes)

    # ------------------------------------------------------------- drive

    @property
    def honest(self) -> List:
        return [n for n in self.nodes if n.name in self.honest_names]

    def run(self, seconds: float) -> "Scenario":
        """Pump the pool for `seconds` of sim time, checking every
        safety invariant after every tick."""
        end = self.timer.get_current_time() + seconds
        while self.timer.get_current_time() < end:
            self._tick()
        return self

    def run_until(self, condition: Callable[[], bool], timeout: float,
                  desc: str) -> "Scenario":
        """Pump until condition() holds; LivenessViolation on timeout —
        the bounded-window liveness assertion."""
        deadline = self.timer.get_current_time() + timeout
        while not condition():
            if self.timer.get_current_time() >= deadline:
                raise LivenessViolation(
                    "liveness: {} not reached within {}s (t={})".format(
                        desc, timeout, self.timer.get_current_time()))
            self._tick()
        return self

    def _tick(self) -> None:
        for node in self.nodes:
            node.service()
        self.timer.run_for(self.step)
        try:
            self.checker.check()
        except AssertionError as e:
            path = self.dump_trace()
            if path:
                logger.error("safety invariant failed — flight-recorder "
                             "timeline dumped to %s (load in "
                             "ui.perfetto.dev)", path)
                if e.args and isinstance(e.args[0], str):
                    e.args = ("%s [flight recorder: %s]"
                              % (e.args[0], path),) + e.args[1:]
            raise

    def dump_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Merge every traced node's ring buffer into one pool-wide
        Chrome trace-event file. → path, or None when no node has
        tracing enabled."""
        from plenum_tpu.observability.export import (
            export_chrome_trace, pool_tracers)
        tracers = [t for t in pool_tracers(self.nodes)
                   if getattr(t, "enabled", False)]
        if not tracers:
            return None
        if path is None:
            out_dir = os.environ.get("PLENUM_TPU_TRACE_DIR") \
                or tempfile.gettempdir()
            _dump_seq[0] += 1
            path = os.path.join(
                out_dir, "invariant_failure_trace_%d_%d.json"
                % (os.getpid(), _dump_seq[0]))
        try:
            return export_chrome_trace(tracers, path)
        except OSError:
            logger.warning("could not write flight-recorder trace to %s",
                           path, exc_info=True)
            return None

    # ------------------------------------------------- liveness helpers

    def await_ordering_resumes(self, extra_batches: int = 1,
                               within: float = 30.0) -> "Scenario":
        """Honest nodes must each order `extra_batches` more batches
        within the window (the fault is over / absorbed)."""
        base = {n.name: _last_seq(n) for n in self.honest}

        def resumed():
            return all(_last_seq(n) >= base[n.name] + extra_batches
                       for n in self.honest)

        return self.run_until(
            resumed, within,
            "+{} ordered batches on every honest node".format(
                extra_batches))

    def await_view_change(self, min_view: int = 1,
                          within: float = 60.0) -> "Scenario":
        """Every honest node must complete a view change to at least
        `min_view` (adversarial-primary recovery)."""

        def done():
            return all(
                _replica(n).view_no >= min_view
                and not _replica(n).data.waiting_for_new_view
                for n in self.honest)

        return self.run_until(
            done, within, "view change to >= {}".format(min_view))


def _replica(node):
    return getattr(node, "replica", node)


def _last_seq(node) -> int:
    return _replica(node).last_ordered[1]
