"""AdversaryController — wraps selected sim-pool nodes and owns a
deterministic, seed-driven fault schedule.

All injection goes through the ONE interception seam
(ReplicaService.install_network_tap → ExternalBus tap); the controller
never reaches into consensus/network internals. Every fault decision
draws from one seeded SimRandom and every action appends to ``trace``
stamped with sim time, so a fixed seed replays the identical fault
sequence — the property the determinism tests pin down."""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from plenum_tpu.runtime.sim_random import DefaultSimRandom, SimRandom
from plenum_tpu.runtime.timer import RepeatingTimer, TimerService

logger = logging.getLogger(__name__)


class _TapChain:
    """The tap object installed on one adversarial node's bus: applies
    each attached behavior in order to every send/receive. A behavior
    returning a replacement list feeds the NEXT behavior message by
    message, so stacked faults compose (e.g. duplicate + lossy link)."""

    def __init__(self, controller: "AdversaryController", node_name: str):
        self._controller = controller
        self._node_name = node_name
        self.behaviors: List = []

    def _apply(self, hook_name: str, msg, meta):
        routed = [(msg, meta)]
        changed = False
        for behavior in self.behaviors:
            hook = getattr(behavior, hook_name)
            nxt = []
            for m, x in routed:
                out = hook(m, x)
                if out is None:
                    nxt.append((m, x))
                else:
                    changed = True
                    nxt.extend(out)
            routed = nxt
        return routed if changed else None

    def on_send(self, msg, dst):
        return self._apply("on_send", msg, dst)

    def on_incoming(self, msg, frm):
        return self._apply("on_incoming", msg, frm)

    def on_tick(self):
        for behavior in self.behaviors:
            behavior.on_tick()


class _Wrapped:
    def __init__(self, install: Callable, uninstall: Callable,
                 raw_send: Callable, chain: _TapChain):
        self.install = install
        self.uninstall = uninstall
        self.raw_send = raw_send
        self.chain = chain


class AdversaryController:
    def __init__(self, timer: TimerService,
                 seed: int = 0,
                 random: Optional[SimRandom] = None,
                 tick_interval: float = 0.1):
        self._timer = timer
        self.random = random or DefaultSimRandom(seed)
        self.seed = seed
        # the deterministic fault trace: [(sim_time, event_str)]
        self.trace: List[Tuple[float, str]] = []
        self._wrapped: Dict[str, _Wrapped] = {}
        self._pool_names: List[str] = []
        self._ticker = RepeatingTimer(timer, tick_interval, self._on_tick,
                                      active=False)

    # ------------------------------------------------------------ roster

    def set_pool(self, nodes) -> None:
        """Tell the controller the full pool roster (used by behaviors
        to materialize broadcast destination sets)."""
        self._pool_names = [self._name_of(n) for n in nodes]

    def pool_names(self) -> List[str]:
        return list(self._pool_names)

    @property
    def adversaries(self) -> List[str]:
        return sorted(self._wrapped)

    # ------------------------------------------------------------- wiring

    @staticmethod
    def _name_of(node) -> str:
        return node if isinstance(node, str) else node.name

    @staticmethod
    def _seam_of(node):
        """Resolve the interception seam of a sim-pool member: a full
        Node exposes it via its master ReplicaService; a bare
        ReplicaService exposes it directly."""
        if hasattr(node, "install_network_tap"):
            return node
        replica = getattr(node, "replica", None)
        if replica is not None and hasattr(replica, "install_network_tap"):
            return replica
        raise TypeError("{!r} exposes no network-tap seam".format(node))

    def corrupt(self, node, behavior) -> "AdversaryController":
        """Attach a Behavior to a node (installing the tap chain through
        the seam on first corruption). Chainable."""
        name = self._name_of(node)
        wrapped = self._wrapped.get(name)
        if wrapped is None:
            seam = self._seam_of(node)
            chain = _TapChain(self, name)
            bus = seam.network
            wrapped = _Wrapped(
                install=lambda: seam.install_network_tap(chain),
                uninstall=seam.uninstall_network_tap,
                raw_send=bus.send_raw,
                chain=chain)
            wrapped.install()
            self._wrapped[name] = wrapped
            if name not in self._pool_names:
                self._pool_names.append(name)
        behavior.attach(name, self)
        wrapped.chain.behaviors.append(behavior)
        self.record("install {} on {}".format(behavior.name, name))
        self._ticker.start()
        return self

    def release(self, node, behavior=None) -> None:
        """Stop one behavior (or all of them) on a node; uninstalls the
        tap when the chain empties so the node runs fully clean."""
        name = self._name_of(node)
        wrapped = self._wrapped.get(name)
        if wrapped is None:
            return
        doomed = [b for b in wrapped.chain.behaviors
                  if behavior is None or b is behavior]
        for b in doomed:
            wrapped.chain.behaviors.remove(b)
            b.detach()
            self.record("release {} on {}".format(b.name, name))
        if not wrapped.chain.behaviors:
            wrapped.uninstall()
            del self._wrapped[name]

    def release_all(self) -> None:
        for name in list(self._wrapped):
            self.release(name)
        self._ticker.stop()

    def partition(self, *groups) -> Dict[str, object]:
        """Split the pool into isolated groups: every node in a group
        gets a Partition behavior whose reachable set is its own group
        (cross-group traffic drops both ways). → {node_name: behavior}
        for heal_partition. Nodes under partition count as 'corrupted'
        for Scenario's default honest-set derivation — partition tests
        pass an explicit honest list."""
        from plenum_tpu.testing.adversary.behaviors import Partition
        behaviors: Dict[str, object] = {}
        for group in groups:
            names = [self._name_of(n) for n in group]
            for node in group:
                behavior = Partition(reachable=names)
                self.corrupt(node, behavior)
                behaviors[self._name_of(node)] = behavior
        self.record("partition {}".format(
            " / ".join("+".join(sorted(self._name_of(n) for n in g))
                       for g in groups)))
        return behaviors

    def heal_partition(self, behaviors: Dict[str, object]) -> None:
        """Remove every Partition behavior installed by partition()."""
        for name, behavior in behaviors.items():
            self.release(name, behavior)
        self.record("partition healed")

    # ---------------------------------------------------------- schedule

    def at(self, delay: float, action: Callable[[], None],
           desc: str = "") -> "AdversaryController":
        """Schedule a fault-plan step at now+delay on the sim timer —
        the deterministic replacement for ad-hoc mid-test mutation."""

        def fire():
            self.record("scheduled: {}".format(desc or action))
            action()

        self._timer.schedule(delay, fire)
        return self

    def _on_tick(self):
        for wrapped in self._wrapped.values():
            wrapped.chain.on_tick()

    # ------------------------------------------------------------- trace

    def now(self) -> float:
        return self._timer.get_current_time()

    def record(self, event: str) -> None:
        self.trace.append((round(self.now(), 6), event))

    def raw_send(self, node_name: str, msg, dst) -> None:
        """Send bypassing the tap (used by behaviors releasing held
        traffic)."""
        wrapped = self._wrapped.get(node_name)
        if wrapped is not None:
            wrapped.raw_send(msg, dst)

    def trace_lines(self) -> List[str]:
        return ["{:.6f} {}".format(t, e) for t, e in self.trace]
