"""Byzantine adversary subsystem: pluggable malicious behaviors, a
deterministic seed-driven fault scheduler, and safety/liveness invariant
checkers evaluated every sim tick.

Reference corpus: plenum/test/malicious_behaviors_node.py (request
tampering, duplicate/conflicting 3PC, malign sending) + the 73
view-change test files. Injection happens ONLY through the interception
seam (ExternalBus tap via ReplicaService.install_network_tap /
NodeStack.wire_tap) — production classes carry zero behavior logic.

Usage sketch::

    adv = AdversaryController(mock_timer, seed=7)
    adv.corrupt(nodes[0], EquivocatingPrimary())
    adv.at(5.0, lambda: adv.release(nodes[0]), "stop equivocation")
    Scenario(mock_timer, nodes, adversary=adv).run(20)   # checks
    # safety invariants every tick, raises InvariantViolation on fork
"""
from plenum_tpu.testing.adversary.behaviors import (  # noqa: F401
    Behavior, ConflictingPrepare, DuplicateThreePC, EquivocatingNewView,
    EquivocatingPrimary, LinkFault, LyingCatchupSeeder, Partition,
    PoisonedBlsShare, SilentNode, TamperedPropagate)
from plenum_tpu.testing.adversary.controller import (  # noqa: F401
    AdversaryController)
from plenum_tpu.testing.adversary.invariants import (  # noqa: F401
    InvariantChecker, InvariantViolation)
from plenum_tpu.testing.adversary.scenario import (  # noqa: F401
    LivenessViolation, Scenario, SLOViolation)
