"""Pluggable malicious behaviors, mirroring the reference corpus
(plenum/test/malicious_behaviors_node.py): equivocating primary,
duplicate/conflicting 3PC, tampered PROPAGATE payloads, poisoned
deferred BLS shares, and per-link delay/reorder/drop/corrupt faults.

A Behavior is a send/recv transformer installed on ONE adversarial
node's network seam by the AdversaryController. Both hooks follow the
ExternalBus tap protocol: return ``None`` to pass the message through
unchanged, or a list of (message, destination) pairs that replaces it
(empty list = swallow). All randomness MUST come from
``self.controller.random`` so a fixed seed reproduces the identical
fault trace."""
from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from plenum_tpu.common.messages.node_messages import (
    CatchupRep, Commit, ConsistencyProof, MessageRep, NewView,
    PrePrepare, Prepare, Propagate, PropagateBatch)

logger = logging.getLogger(__name__)


class Behavior:
    """Base: benign pass-through. Subclasses override on_send /
    on_incoming / on_tick."""

    name = "behavior"

    def __init__(self):
        self.controller = None
        self.node_name = None

    def attach(self, node_name: str, controller) -> None:
        self.node_name = node_name
        self.controller = controller

    def detach(self) -> None:
        pass

    def record(self, event: str) -> None:
        self.controller.record("{}[{}] {}".format(
            self.name, self.node_name, event))

    def on_send(self, msg, dst) -> Optional[List[Tuple]]:
        return None

    def on_incoming(self, msg, frm) -> Optional[List[Tuple]]:
        return None

    def on_tick(self) -> None:
        """Deterministic scheduler tick (release held messages etc.)."""


def _broadcast_targets(controller, node_name, dst) -> List[str]:
    """Materialize a send's destination set from the pool roster."""
    if dst is None:
        return [n for n in controller.pool_names() if n != node_name]
    if isinstance(dst, str):
        return [dst]
    return list(dst)


class EquivocatingPrimary(Behavior):
    """The primary proposes DIFFERENT batches to different replicas
    (reference: malicious send of conflicting PRE-PREPAREs). Half the
    recipients get the real PRE-PREPARE; the other half get a forged
    variant with the batch contents stripped and the digest recomputed
    (so it passes the digest check and fails only at the apply-and-
    compare defense — the strongest equivocation an adversary without
    the honest executor state can mount)."""

    name = "equivocate-pp"

    def __init__(self, real_count: Optional[int] = None):
        """real_count: how many recipients get the REAL PrePrepare
        (None = half). 0 = everyone gets the forged variant — the
        stall-inducing extreme; >=1 leaves a seed for MessageReq
        self-healing."""
        super().__init__()
        self._real_count = real_count

    def on_send(self, msg, dst):
        if not isinstance(msg, PrePrepare):
            return None
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        if len(targets) < 2:
            return None
        shuffled = self.controller.random.shuffle(sorted(targets))
        half = max(1, len(shuffled) // 2) if self._real_count is None \
            else max(0, min(self._real_count, len(shuffled)))
        group_a, group_b = shuffled[:half], shuffled[half:]
        if not group_b:
            return None
        from plenum_tpu.consensus.ordering_service import OrderingService
        params = dict(msg.as_dict())
        params["reqIdr"] = []
        ov = params.get("originalViewNo")
        params["digest"] = OrderingService.generate_pp_digest(
            [], ov if ov is not None else msg.viewNo, msg.ppTime)
        forged = PrePrepare(**params)
        self.record("pp seq={} real->{} forged->{}".format(
            msg.ppSeqNo, ",".join(sorted(group_a)) or "-",
            ",".join(sorted(group_b))))
        out = [(forged, group_b)]
        if group_a:
            out.insert(0, (msg, group_a))
        return out


class DuplicateThreePC(Behavior):
    """Every outgoing 3PC message is sent `copies` times (reference
    duplicate-3PC malicious behavior). Honest nodes must count each
    sender once per (view, seq)."""

    name = "duplicate-3pc"

    def __init__(self, copies: int = 3, message_types=(PrePrepare,
                                                       Prepare, Commit)):
        super().__init__()
        self._copies = copies
        self._types = tuple(message_types)

    def on_send(self, msg, dst):
        if not isinstance(msg, self._types):
            return None
        self.record("x{} {} seq={}".format(
            self._copies, type(msg).__name__,
            getattr(msg, "ppSeqNo", "?")))
        return [(msg, dst)] * self._copies


class ConflictingPrepare(Behavior):
    """A non-primary vote-splitter: victims receive a PREPARE whose
    digest disagrees with the PRE-PREPARE (reference conflicting-3PC
    behavior); everyone else gets the real vote. Honest nodes must
    discard the conflicting vote (PR_DIGEST_WRONG) and still reach
    quorum from honest votes."""

    name = "conflicting-prepare"

    def __init__(self, victims=None):
        super().__init__()
        self._victims = set(victims) if victims is not None else None

    def on_send(self, msg, dst):
        if not isinstance(msg, Prepare):
            return None
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        victims = [t for t in targets
                   if self._victims is None or t in self._victims]
        rest = [t for t in targets if t not in victims]
        if not victims:
            return None
        params = dict(msg.as_dict())
        params["digest"] = "f" * len(msg.digest)
        conflicting = Prepare(**params)
        self.record("seq={} conflicting->{}".format(
            msg.ppSeqNo, ",".join(sorted(victims))))
        out = [(conflicting, victims)]
        if rest:
            out.append((msg, rest))
        return out


class TamperedPropagate(Behavior):
    """Request tampering (reference malicious_behaviors_node
    changesRequest): every relayed PROPAGATE carries a mutated
    operation. The tampered copy hashes to a different digest, so it
    can never join the f+1 identical-propagate quorum of the honest
    request — finalization must come from honest relays only."""

    name = "tamper-propagate"

    def _tamper(self, request: dict) -> dict:
        req = dict(request)
        op = dict(req.get("operation") or {})
        op["dest"] = "Tampered" + str(op.get("dest", ""))[:20]
        req["operation"] = op
        return req

    def on_send(self, msg, dst):
        if isinstance(msg, Propagate):
            self.record("tampered propagate req={}".format(
                (msg.request or {}).get("reqId")))
            return [(Propagate(request=self._tamper(msg.request),
                               senderClient=msg.senderClient), dst)]
        if isinstance(msg, PropagateBatch):
            self.record("tampered propagate batch n={}".format(
                len(msg.requests)))
            return [(PropagateBatch(
                requests=[self._tamper(r) for r in msg.requests],
                clients=list(msg.clients)), dst)]
        return None


class PoisonedBlsShare(Behavior):
    """COMMITs carry a BLS share that decodes fine but signs the WRONG
    value (a stale share from an earlier batch), or — every `garble_every`
    poisonings — an undecodable string. Drives the deferred-verification
    defense in consensus/bls_bft_replica.py: the aggregate check fails,
    the per-share unroll assigns blame, the adaptive strict window
    engages, and the multi-sig backfill recovers the proof from late
    honest shares."""

    name = "poison-bls"

    def __init__(self, garble_every: int = 0):
        super().__init__()
        self._stale_sig = None
        self._garble_every = garble_every
        self._count = 0

    def on_send(self, msg, dst):
        if not isinstance(msg, Commit) or \
                getattr(msg, "blsSig", None) is None:
            return None
        self._count += 1
        stale, self._stale_sig = self._stale_sig, msg.blsSig
        if self._garble_every and self._count % self._garble_every == 0:
            poisoned = "!!not-base58!!"
        elif stale is not None and stale != msg.blsSig:
            poisoned = stale          # valid share over the wrong value
        else:
            poisoned = msg.blsSig[::-1]
        params = dict(msg.as_dict())
        params["blsSig"] = poisoned
        self.record("seq={} poisoned".format(msg.ppSeqNo))
        return [(Commit(**params), dst)]


class SilentNode(Behavior):
    """A crashed (or byzantine-silent) node: every outgoing message is
    swallowed, and optionally every incoming one too. Installed on the
    primary this is the classic fail-stop failover scenario — honest
    nodes' disconnect/freshness watchdogs must vote a view change and
    ordering must resume under the new primary. Unlike
    SimNetwork.disconnect it keeps the transport 'connected' (no
    Disconnected events), which is the HARD variant: a hung process
    holds its sockets open, so only protocol-level timeouts can notice."""

    name = "silent-node"

    def __init__(self, drop_incoming: bool = True,
                 message_types=None):
        """message_types: restrict the silence (None = everything) —
        e.g. only 3PC messages, keeping heartbeats alive."""
        super().__init__()
        self._drop_incoming = drop_incoming
        self._types = tuple(message_types) if message_types else None
        self._dropped = 0

    def _silent_for(self, msg) -> bool:
        return self._types is None or isinstance(msg, self._types)

    def on_send(self, msg, dst):
        if not self._silent_for(msg):
            return None
        self._dropped += 1
        if self._dropped == 1:
            self.record("went silent")
        return []

    def on_incoming(self, msg, frm):
        if not self._drop_incoming or not self._silent_for(msg):
            return None
        return []


class EquivocatingNewView(Behavior):
    """A byzantine NEW primary abusing the one message only it may
    send. Modes:

    * ``equivocate`` — `real_count` recipients (None = half) get the
      honest NEW_VIEW; the rest get a forgery with a tampered
      checkpoint digest. Honest validators recompute the decision from
      the referenced VIEW_CHANGEs (``_finish_view_change``), detect the
      mismatch and vote the next view — the pool must converge past
      the equivocator.
    * ``stale`` — the first NEW_VIEW is swallowed and every later one
      is replaced by the previously captured (now stale) message, which
      receivers discard as an old view. Nobody ever completes the view
      change under this primary, so the NEW_VIEW timeout (and its
      escalation) is what recovers the pool.
    """

    name = "equivocate-nv"

    def __init__(self, mode: str = "equivocate",
                 real_count: Optional[int] = None):
        assert mode in ("equivocate", "stale")
        super().__init__()
        self._mode = mode
        self._real_count = real_count
        self._last: Optional[NewView] = None

    @staticmethod
    def _forge(msg: NewView) -> NewView:
        params = dict(msg.as_dict())
        chk = dict(params.get("checkpoint") or {})
        chk["digest"] = "forged-" + str(chk.get("digest", ""))[:32]
        params["checkpoint"] = chk
        return NewView(**params)

    def on_send(self, msg, dst):
        # a NEW_VIEW answer to a peer's re-request (MessageRep) is the
        # same message on a different path — a byzantine primary lies
        # there too, or the self-heal re-request would fetch the honest
        # NEW_VIEW straight out of the liar's own store
        if isinstance(msg, MessageRep) and msg.msg_type == "NEW_VIEW" \
                and msg.msg is not None:
            if self._mode == "stale":
                # swallowing is the stale liar's reply-path analogue:
                # `_last` already holds the CURRENT honest NEW_VIEW, so
                # replaying it here would heal the victims
                self.record("NEW_VIEW rep swallowed")
                return []
            forged = self._forge(NewView(**msg.msg))
            self.record("NEW_VIEW rep forged")
            return [(MessageRep(msg_type=msg.msg_type, params=msg.params,
                                msg=forged.as_dict()), dst)]
        if not isinstance(msg, NewView):
            return None
        if self._mode == "stale":
            prev, self._last = self._last, msg
            if prev is None:
                self.record("view={} NEW_VIEW swallowed".format(
                    msg.viewNo))
                return []
            self.record("view={} replaced by stale view={}".format(
                msg.viewNo, prev.viewNo))
            return [(prev, dst)]
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        if not targets:
            return None
        shuffled = self.controller.random.shuffle(sorted(targets))
        half = max(0, len(shuffled) // 2) if self._real_count is None \
            else max(0, min(self._real_count, len(shuffled)))
        group_real, group_forged = shuffled[:half], shuffled[half:]
        if not group_forged:
            return None
        self.record("view={} real->{} forged->{}".format(
            msg.viewNo, ",".join(sorted(group_real)) or "-",
            ",".join(sorted(group_forged))))
        out = [(self._forge(msg), group_forged)]
        if group_real:
            out.insert(0, (msg, group_real))
        return out


class LyingCatchupSeeder(Behavior):
    """A byzantine catchup provider: consistency proofs advertise a
    forged root (they can never reach the leecher's quorum, only delay
    it), and catchup reps are garbled — the per-txn content is mutated
    while the audit paths still claim the honest range, so a leecher
    verifying against the quorum-agreed root rejects the chunk at rep
    time, marks this peer bad, and re-requests elsewhere. ``stall_every``
    > 0 swallows every Nth rep instead (the silent-stall variant that
    only the retry backoff + peer rotation can route around)."""

    name = "lying-seeder"

    def __init__(self, lie_cons_proofs: bool = True,
                 garble_reps: bool = True, stall_every: int = 0):
        super().__init__()
        self._lie_proofs = lie_cons_proofs
        self._garble = garble_reps
        self._stall_every = stall_every
        self._reps = 0

    def on_send(self, msg, dst):
        if isinstance(msg, ConsistencyProof) and self._lie_proofs:
            from plenum_tpu.ledger.ledger import Ledger
            params = dict(msg.as_dict())
            params["newMerkleRoot"] = Ledger.hashToStr(
                b"\x11" * 32)
            self.record("lied cons-proof {}..{}".format(
                msg.seqNoStart, msg.seqNoEnd))
            return [(ConsistencyProof(**params), dst)]
        if isinstance(msg, CatchupRep):
            self._reps += 1
            if self._stall_every and \
                    self._reps % self._stall_every == 0:
                self.record("stalled rep n={}".format(len(msg.txns)))
                return []
            if self._garble:
                garbled = {seq: dict(txn, lie=self._reps)
                           for seq, txn in msg.txns.items()}
                self.record("garbled rep n={}".format(len(garbled)))
                return [(CatchupRep(
                    ledgerId=msg.ledgerId, txns=garbled,
                    consProof=list(msg.consProof),
                    auditPaths=getattr(msg, "auditPaths", None)), dst)]
        return None


class Partition(Behavior):
    """One side of a network partition: sends reach only the peers in
    ``reachable`` and incoming traffic from outside it is dropped.
    Install one instance per node with reachable = that node's own
    group (AdversaryController.partition wires a whole pool split);
    releasing the behaviors heals the partition — LinkFault-style held
    state does not exist here, so healing is instantaneous."""

    name = "partition"

    def __init__(self, reachable):
        super().__init__()
        self._reachable = set(reachable)

    def on_send(self, msg, dst):
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        kept = [t for t in targets if t in self._reachable]
        if len(kept) == len(targets):
            return None
        return [(msg, kept)] if kept else []

    def on_incoming(self, msg, frm):
        if frm in self._reachable:
            return None
        return []


class LinkFault(Behavior):
    """Per-link chaos: probabilistic drop / corrupt / delay (delay with
    jitter ⇒ reorder) on matching sends. All draws come from the
    controller's seeded SimRandom; held messages are released by the
    controller's deterministic tick, so the whole fault pattern replays
    bit-identically for a fixed seed."""

    name = "link-fault"

    def __init__(self, drop_p: float = 0.0, corrupt_p: float = 0.0,
                 delay_p: float = 0.0, delay: float = 1.0,
                 jitter: float = 0.5, dst=None, message_types=None):
        super().__init__()
        self._drop_p = drop_p
        self._corrupt_p = corrupt_p
        self._delay_p = delay_p
        self._delay = delay
        self._jitter = jitter
        self._dst = set(dst) if dst is not None else None
        self._types = tuple(message_types) if message_types else None
        self._held: List[Tuple[float, object, object]] = []

    def _matches(self, msg, dst) -> bool:
        if self._types is not None and not isinstance(msg, self._types):
            return False
        if self._dst is not None:
            targets = _broadcast_targets(self.controller, self.node_name,
                                         dst)
            return bool(set(targets) & self._dst)
        return True

    def _corrupt(self, msg):
        if hasattr(msg, "digest") and isinstance(msg.digest, str):
            params = dict(msg.as_dict())
            params["digest"] = "0" * len(msg.digest)
            return type(msg)(**params)
        return msg

    def on_send(self, msg, dst):
        if not self._matches(msg, dst):
            return None
        rng = self.controller.random
        roll = rng.float(0.0, 1.0)
        if roll < self._drop_p:
            self.record("drop {}".format(type(msg).__name__))
            return []
        if roll < self._drop_p + self._corrupt_p:
            self.record("corrupt {}".format(type(msg).__name__))
            return [(self._corrupt(msg), dst)]
        if roll < self._drop_p + self._corrupt_p + self._delay_p:
            extra = self._delay + rng.float(0.0, self._jitter)
            release = self.controller.now() + extra
            self._held.append((release, msg, dst))
            self.record("hold {} for {:.2f}s".format(
                type(msg).__name__, extra))
            return []
        return None

    def on_tick(self):
        now = self.controller.now()
        due = [h for h in self._held if h[0] <= now]
        if not due:
            return
        self._held = [h for h in self._held if h[0] > now]
        for _, msg, dst in due:
            self.controller.raw_send(self.node_name, msg, dst)

    def detach(self):
        # flush anything still held so messages are not lost forever
        for _, msg, dst in self._held:
            self.controller.raw_send(self.node_name, msg, dst)
        self._held = []
