"""Pluggable malicious behaviors, mirroring the reference corpus
(plenum/test/malicious_behaviors_node.py): equivocating primary,
duplicate/conflicting 3PC, tampered PROPAGATE payloads, poisoned
deferred BLS shares, and per-link delay/reorder/drop/corrupt faults.

A Behavior is a send/recv transformer installed on ONE adversarial
node's network seam by the AdversaryController. Both hooks follow the
ExternalBus tap protocol: return ``None`` to pass the message through
unchanged, or a list of (message, destination) pairs that replaces it
(empty list = swallow). All randomness MUST come from
``self.controller.random`` so a fixed seed reproduces the identical
fault trace."""
from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from plenum_tpu.common.messages.node_messages import (
    Commit, PrePrepare, Prepare, Propagate, PropagateBatch)

logger = logging.getLogger(__name__)


class Behavior:
    """Base: benign pass-through. Subclasses override on_send /
    on_incoming / on_tick."""

    name = "behavior"

    def __init__(self):
        self.controller = None
        self.node_name = None

    def attach(self, node_name: str, controller) -> None:
        self.node_name = node_name
        self.controller = controller

    def detach(self) -> None:
        pass

    def record(self, event: str) -> None:
        self.controller.record("{}[{}] {}".format(
            self.name, self.node_name, event))

    def on_send(self, msg, dst) -> Optional[List[Tuple]]:
        return None

    def on_incoming(self, msg, frm) -> Optional[List[Tuple]]:
        return None

    def on_tick(self) -> None:
        """Deterministic scheduler tick (release held messages etc.)."""


def _broadcast_targets(controller, node_name, dst) -> List[str]:
    """Materialize a send's destination set from the pool roster."""
    if dst is None:
        return [n for n in controller.pool_names() if n != node_name]
    if isinstance(dst, str):
        return [dst]
    return list(dst)


class EquivocatingPrimary(Behavior):
    """The primary proposes DIFFERENT batches to different replicas
    (reference: malicious send of conflicting PRE-PREPAREs). Half the
    recipients get the real PRE-PREPARE; the other half get a forged
    variant with the batch contents stripped and the digest recomputed
    (so it passes the digest check and fails only at the apply-and-
    compare defense — the strongest equivocation an adversary without
    the honest executor state can mount)."""

    name = "equivocate-pp"

    def __init__(self, real_count: Optional[int] = None):
        """real_count: how many recipients get the REAL PrePrepare
        (None = half). 0 = everyone gets the forged variant — the
        stall-inducing extreme; >=1 leaves a seed for MessageReq
        self-healing."""
        super().__init__()
        self._real_count = real_count

    def on_send(self, msg, dst):
        if not isinstance(msg, PrePrepare):
            return None
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        if len(targets) < 2:
            return None
        shuffled = self.controller.random.shuffle(sorted(targets))
        half = max(1, len(shuffled) // 2) if self._real_count is None \
            else max(0, min(self._real_count, len(shuffled)))
        group_a, group_b = shuffled[:half], shuffled[half:]
        if not group_b:
            return None
        from plenum_tpu.consensus.ordering_service import OrderingService
        params = dict(msg.as_dict())
        params["reqIdr"] = []
        ov = params.get("originalViewNo")
        params["digest"] = OrderingService.generate_pp_digest(
            [], ov if ov is not None else msg.viewNo, msg.ppTime)
        forged = PrePrepare(**params)
        self.record("pp seq={} real->{} forged->{}".format(
            msg.ppSeqNo, ",".join(sorted(group_a)) or "-",
            ",".join(sorted(group_b))))
        out = [(forged, group_b)]
        if group_a:
            out.insert(0, (msg, group_a))
        return out


class DuplicateThreePC(Behavior):
    """Every outgoing 3PC message is sent `copies` times (reference
    duplicate-3PC malicious behavior). Honest nodes must count each
    sender once per (view, seq)."""

    name = "duplicate-3pc"

    def __init__(self, copies: int = 3, message_types=(PrePrepare,
                                                       Prepare, Commit)):
        super().__init__()
        self._copies = copies
        self._types = tuple(message_types)

    def on_send(self, msg, dst):
        if not isinstance(msg, self._types):
            return None
        self.record("x{} {} seq={}".format(
            self._copies, type(msg).__name__,
            getattr(msg, "ppSeqNo", "?")))
        return [(msg, dst)] * self._copies


class ConflictingPrepare(Behavior):
    """A non-primary vote-splitter: victims receive a PREPARE whose
    digest disagrees with the PRE-PREPARE (reference conflicting-3PC
    behavior); everyone else gets the real vote. Honest nodes must
    discard the conflicting vote (PR_DIGEST_WRONG) and still reach
    quorum from honest votes."""

    name = "conflicting-prepare"

    def __init__(self, victims=None):
        super().__init__()
        self._victims = set(victims) if victims is not None else None

    def on_send(self, msg, dst):
        if not isinstance(msg, Prepare):
            return None
        targets = _broadcast_targets(self.controller, self.node_name, dst)
        victims = [t for t in targets
                   if self._victims is None or t in self._victims]
        rest = [t for t in targets if t not in victims]
        if not victims:
            return None
        params = dict(msg.as_dict())
        params["digest"] = "f" * len(msg.digest)
        conflicting = Prepare(**params)
        self.record("seq={} conflicting->{}".format(
            msg.ppSeqNo, ",".join(sorted(victims))))
        out = [(conflicting, victims)]
        if rest:
            out.append((msg, rest))
        return out


class TamperedPropagate(Behavior):
    """Request tampering (reference malicious_behaviors_node
    changesRequest): every relayed PROPAGATE carries a mutated
    operation. The tampered copy hashes to a different digest, so it
    can never join the f+1 identical-propagate quorum of the honest
    request — finalization must come from honest relays only."""

    name = "tamper-propagate"

    def _tamper(self, request: dict) -> dict:
        req = dict(request)
        op = dict(req.get("operation") or {})
        op["dest"] = "Tampered" + str(op.get("dest", ""))[:20]
        req["operation"] = op
        return req

    def on_send(self, msg, dst):
        if isinstance(msg, Propagate):
            self.record("tampered propagate req={}".format(
                (msg.request or {}).get("reqId")))
            return [(Propagate(request=self._tamper(msg.request),
                               senderClient=msg.senderClient), dst)]
        if isinstance(msg, PropagateBatch):
            self.record("tampered propagate batch n={}".format(
                len(msg.requests)))
            return [(PropagateBatch(
                requests=[self._tamper(r) for r in msg.requests],
                clients=list(msg.clients)), dst)]
        return None


class PoisonedBlsShare(Behavior):
    """COMMITs carry a BLS share that decodes fine but signs the WRONG
    value (a stale share from an earlier batch), or — every `garble_every`
    poisonings — an undecodable string. Drives the deferred-verification
    defense in consensus/bls_bft_replica.py: the aggregate check fails,
    the per-share unroll assigns blame, the adaptive strict window
    engages, and the multi-sig backfill recovers the proof from late
    honest shares."""

    name = "poison-bls"

    def __init__(self, garble_every: int = 0):
        super().__init__()
        self._stale_sig = None
        self._garble_every = garble_every
        self._count = 0

    def on_send(self, msg, dst):
        if not isinstance(msg, Commit) or \
                getattr(msg, "blsSig", None) is None:
            return None
        self._count += 1
        stale, self._stale_sig = self._stale_sig, msg.blsSig
        if self._garble_every and self._count % self._garble_every == 0:
            poisoned = "!!not-base58!!"
        elif stale is not None and stale != msg.blsSig:
            poisoned = stale          # valid share over the wrong value
        else:
            poisoned = msg.blsSig[::-1]
        params = dict(msg.as_dict())
        params["blsSig"] = poisoned
        self.record("seq={} poisoned".format(msg.ppSeqNo))
        return [(Commit(**params), dst)]


class LinkFault(Behavior):
    """Per-link chaos: probabilistic drop / corrupt / delay (delay with
    jitter ⇒ reorder) on matching sends. All draws come from the
    controller's seeded SimRandom; held messages are released by the
    controller's deterministic tick, so the whole fault pattern replays
    bit-identically for a fixed seed."""

    name = "link-fault"

    def __init__(self, drop_p: float = 0.0, corrupt_p: float = 0.0,
                 delay_p: float = 0.0, delay: float = 1.0,
                 jitter: float = 0.5, dst=None, message_types=None):
        super().__init__()
        self._drop_p = drop_p
        self._corrupt_p = corrupt_p
        self._delay_p = delay_p
        self._delay = delay
        self._jitter = jitter
        self._dst = set(dst) if dst is not None else None
        self._types = tuple(message_types) if message_types else None
        self._held: List[Tuple[float, object, object]] = []

    def _matches(self, msg, dst) -> bool:
        if self._types is not None and not isinstance(msg, self._types):
            return False
        if self._dst is not None:
            targets = _broadcast_targets(self.controller, self.node_name,
                                         dst)
            return bool(set(targets) & self._dst)
        return True

    def _corrupt(self, msg):
        if hasattr(msg, "digest") and isinstance(msg.digest, str):
            params = dict(msg.as_dict())
            params["digest"] = "0" * len(msg.digest)
            return type(msg)(**params)
        return msg

    def on_send(self, msg, dst):
        if not self._matches(msg, dst):
            return None
        rng = self.controller.random
        roll = rng.float(0.0, 1.0)
        if roll < self._drop_p:
            self.record("drop {}".format(type(msg).__name__))
            return []
        if roll < self._drop_p + self._corrupt_p:
            self.record("corrupt {}".format(type(msg).__name__))
            return [(self._corrupt(msg), dst)]
        if roll < self._drop_p + self._corrupt_p + self._delay_p:
            extra = self._delay + rng.float(0.0, self._jitter)
            release = self.controller.now() + extra
            self._held.append((release, msg, dst))
            self.record("hold {} for {:.2f}s".format(
                type(msg).__name__, extra))
            return []
        return None

    def on_tick(self):
        now = self.controller.now()
        due = [h for h in self._held if h[0] <= now]
        if not due:
            return
        self._held = [h for h in self._held if h[0] > now]
        for _, msg, dst in due:
            self.controller.raw_send(self.node_name, msg, dst)

    def detach(self):
        # flush anything still held so messages are not lost forever
        for _, msg, dst in self._held:
            self.controller.raw_send(self.node_name, msg, dst)
        self._held = []
