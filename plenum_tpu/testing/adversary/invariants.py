"""Safety invariant checkers evaluated every sim tick.

Safety (checked continuously by Scenario.run):
  1. AGREEMENT — no two honest nodes order different batches at the
     same (original_view, seqNo): digest, state/txn roots and request
     set must match across nodes AND across time (a node must never
     rewrite its own history).
  2. LEDGER CONSISTENCY — honest nodes' ledgers agree at every size
     they both reach (checkpoint convergence: same prefix ⇒ same root).
  3. PROOF HONESTY — no honest node stores a BLS multi-sig over a
     state root that honest nodes did not order (a poisoned share must
     never smuggle a proof for a root the pool disagrees on).

Liveness (bounded-window assertions driven by Scenario helpers, not
every tick): ordering resumes after the fault stops; the view change
completes when the primary is the adversary."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class InvariantViolation(AssertionError):
    """A byzantine-safety invariant broke — the pool forked."""


def _replica_of(node):
    return getattr(node, "replica", node)


class InvariantChecker:
    def __init__(self, nodes, honest_names: Optional[List[str]] = None):
        self._nodes = list(nodes)
        self._honest = set(honest_names) if honest_names is not None \
            else {n.name for n in nodes}
        # (orig_view, seq) -> (digest, state_root, txn_root, reqs) agreed
        # by the first honest orderer; every later observation must match
        self._ordered_history: Dict[Tuple[int, int], Tuple] = {}
        self._ordered_by: Dict[Tuple[int, int], str] = {}
        # per-node count of ordered_log entries already folded in
        self._seen_ordered: Dict[str, int] = {}
        # ledger label -> size -> (root, first_node)
        self._ledger_roots: Dict[str, Dict[int, Tuple[str, str]]] = {}
        self.checks = 0

    # ------------------------------------------------------------ public

    def honest_nodes(self) -> List:
        return [n for n in self._nodes if n.name in self._honest]

    def ordered_state_roots(self) -> set:
        return {v[1] for v in self._ordered_history.values()
                if v[1] is not None}

    def check(self) -> None:
        """Run every safety invariant; raises InvariantViolation."""
        self.checks += 1
        for node in self.honest_nodes():
            self._check_agreement(node)
        for node in self.honest_nodes():
            self._check_ledgers(node)
        roots = self.ordered_state_roots()
        for node in self.honest_nodes():
            self._check_multisigs(node, roots)

    # ------------------------------------------------- 1: agreement

    def _check_agreement(self, node) -> None:
        replica = _replica_of(node)
        log = replica.ordered_log
        start = self._seen_ordered.get(node.name, 0)
        for ordered in log[start:]:
            ov = ordered.originalViewNo \
                if ordered.originalViewNo is not None else ordered.viewNo
            key = (ov, ordered.ppSeqNo)
            value = (ordered.digest, ordered.stateRootHash,
                     ordered.txnRootHash,
                     tuple(ordered.valid_reqIdr))
            agreed = self._ordered_history.get(key)
            if agreed is None:
                self._ordered_history[key] = value
                self._ordered_by[key] = node.name
            elif agreed != value:
                raise InvariantViolation(
                    "SAFETY FORK at {}: {} ordered {} but {} ordered {}"
                    .format(key, self._ordered_by[key], agreed,
                            node.name, value))
        self._seen_ordered[node.name] = len(log)

    # ------------------------------------------- 2: ledger consistency

    def _check_ledgers(self, node) -> None:
        for label in ("domain_ledger", "audit_ledger"):
            ledger = getattr(node, label, None)
            if ledger is None:
                continue
            size, root = ledger.size, ledger.root_hash
            seen = self._ledger_roots.setdefault(label, {})
            agreed = seen.get(size)
            if agreed is None:
                seen[size] = (root, node.name)
            elif agreed[0] != root:
                raise InvariantViolation(
                    "LEDGER FORK: {} size {} — {} has root {} but {} "
                    "has {}".format(label, size, agreed[1], agreed[0],
                                    node.name, root))

    # --------------------------------------------- 3: proof honesty

    def _check_multisigs(self, node, honest_roots: set) -> None:
        bls = getattr(node, "bls_bft_replica", None)
        if bls is None:
            bls = getattr(_replica_of(node).ordering, "_bls", None)
        store = getattr(bls, "bls_store", None)
        if store is None or not honest_roots:
            return
        for root, multi in store.items():
            if root not in honest_roots:
                raise InvariantViolation(
                    "DISHONEST PROOF: {} stores a multi-sig over state "
                    "root {} which no honest node ordered"
                    .format(node.name, root))
            if multi.value.state_root_hash != root:
                raise InvariantViolation(
                    "CORRUPT PROOF: {} multi-sig keyed {} signs root {}"
                    .format(node.name, root,
                            multi.value.state_root_hash))
