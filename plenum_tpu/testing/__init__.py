from plenum_tpu.testing.mock_timer import MockTimer  # noqa: F401
from plenum_tpu.testing.sim_network import SimNetwork  # noqa: F401
