"""Gateway intake — untrusted client envelopes in, screened batches out.

Three jobs, all at the trust boundary between "millions of users" and
the consensus pool:

* **Wire guard.** Client-facing senders speak the PR-11 ``FLAT_WIRE``
  PROPAGATE envelope (one parse per envelope, request blobs behind a
  u32 offset table). Every structural violation — bad magic, version
  skew, truncated or over-length payload (``parse_envelope``'s
  ``max_bytes`` bound, wired to ``Config.MSG_LEN_LIMIT``) — is
  attributable to the sender: the sender takes a strike and, past
  ``GATEWAY_SENDER_STRIKES``, is shed (envelopes dropped unread).
  Nothing a sender puts on the wire can raise past ``unpack_client``
  — the intake loop cannot crash. Entry-level garbage (an
  undecodable request blob) costs only that entry, the flat-wire
  contract.
* **Dedup.** Retried and multiply-routed requests are collapsed on
  ``(identifier, reqId, signature)`` before any signature work — the
  same pure-function argument as ``dedup_items``: co-arriving copies
  of one request need one verdict.
* **Batched pre-screen.** Every admitted write's ed25519 signature
  joins ONE device dispatch through the injected verifier (the
  standalone ``CoalescingVerifierHub``) — the paper's batched-verify
  amortization applied where the fan-in is widest. The pre-screen is
  a FILTER, not the authority: nodes re-authenticate everything the
  gateway forwards (defense in depth — a compromised gateway can
  deny service, never forge admission), which is also why the
  admitted stream produces byte-identical ledger/state roots with or
  without a gateway in front.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import List, Optional, Tuple

from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.common.serializers.base58 import b58decode
from plenum_tpu.common.serializers.serialization import (
    serialize_msg_for_signing)
from plenum_tpu.crypto.signer import verkey_from_identifier
from plenum_tpu.observability.telemetry import TM, NullTelemetryHub
from plenum_tpu.observability.tracing import CAT_INTAKE, NullTracer

logger = logging.getLogger(__name__)

# dedup window: recently-seen request identities (client-chosen, so
# bounded); far above any one intake batch, far below allocation-attack
# territory
DEDUP_WINDOW_MAX = 1 << 16


class SenderRegistry:
    """Strike accounting for client-facing senders. Bounded LRU — the
    sender id space is client-chosen, so the registry must not be an
    allocation attack; evicting a stranger's strike record only
    forgives, never falsely sheds."""

    def __init__(self, strikes: int = None, max_senders: int = None,
                 telemetry=None):
        from plenum_tpu.common.config import Config
        self.strikes = int(Config.GATEWAY_SENDER_STRIKES
                           if strikes is None else strikes)
        self.max_senders = int(Config.GATEWAY_SENDER_REGISTRY_MAX
                               if max_senders is None else max_senders)
        self._counts: "OrderedDict[str, int]" = OrderedDict()
        self._tm = telemetry if telemetry is not None \
            else NullTelemetryHub()

    def is_shed(self, sender: str) -> bool:
        n = self._counts.get(sender)
        return n is not None and n >= self.strikes

    def strike(self, sender: str) -> bool:
        """One structural violation by ``sender``; → True when the
        sender is (now) shed."""
        n = self._counts.get(sender, 0) + 1
        self._counts[sender] = n
        self._counts.move_to_end(sender)
        while len(self._counts) > self.max_senders:
            self._counts.popitem(last=False)
        if n == self.strikes:
            self._tm.count(TM.GATEWAY_SHED_SENDERS, 1)
        return n >= self.strikes


class GatewayIntake:
    """The screening pipeline. Collaborators are all injected —
    ``verifier`` (any batch_verifier provider; a standalone
    ``CoalescingVerifierHub`` in production), ``verkey_provider``
    (identifier → verkey str, e.g. pool-state-backed; None falls back
    to cryptonym identifiers), ``telemetry`` (the gateway's hub) —
    so the intake runs without a Node, the satellite-1 point."""

    def __init__(self, verifier=None, verkey_provider=None,
                 senders: SenderRegistry = None, telemetry=None,
                 max_envelope_bytes: int = None, tracer=None):
        from plenum_tpu.common.config import Config
        if verifier is None:
            from plenum_tpu.crypto.batch_verifier import (
                CoalescingVerifierHub)
            verifier = CoalescingVerifierHub(telemetry=telemetry)
        self._verifier = verifier
        self._verkeys = verkey_provider
        self._tm = telemetry if telemetry is not None \
            else NullTelemetryHub()
        self.tracer = tracer if tracer is not None else NullTracer()
        self.senders = senders if senders is not None \
            else SenderRegistry(telemetry=self._tm)
        self.max_envelope_bytes = int(Config.MSG_LEN_LIMIT
                                      if max_envelope_bytes is None
                                      else max_envelope_bytes)
        self._seen: "OrderedDict[tuple, None]" = OrderedDict()

    # ------------------------------------------------------ wire guard

    def unpack_client(self, data, sender: str
                      ) -> Optional[List[Tuple[dict, str]]]:
        """One client-facing FLAT_WIRE envelope → [(request dict,
        client id)], or None when the envelope was dropped (sender
        already shed, or struck for this structural violation). Never
        raises on sender-controlled bytes."""
        if self.senders.is_shed(sender):
            return None
        try:
            env = flat_wire.parse_envelope(
                data, max_bytes=self.max_envelope_bytes)
        except flat_wire.FlatWireError as e:
            self._strike(sender, str(e))
            return None
        out: List[Tuple[dict, str]] = []
        for sec in env.sections:
            if sec.kind != flat_wire.KIND_PROPAGATE:
                # a client has no business sending 3PC sections; the
                # whole envelope is sender-attributable garbage
                self._strike(sender, "non-PROPAGATE section %d at the "
                                     "client boundary" % sec.kind)
                return None
            for i in range(sec.n):
                try:
                    req = sec.request(i)
                except Exception:
                    logger.warning("gateway: bad request entry from %s "
                                   "— dropped", sender)
                    continue
                out.append((req, sec.client(i) or sender))
        return out

    def _strike(self, sender: str, why: str) -> None:
        self._tm.count(TM.WIRE_MALFORMED, 1)
        shed = self.senders.strike(sender)
        logger.warning("gateway: malformed envelope from %s (%s)%s",
                       sender, why, " — sender shed" if shed else "")

    # ----------------------------------------------------------- dedup

    def fresh_only(self, msgs: List[Tuple[dict, str]]
                   ) -> List[Tuple[dict, str]]:
        """Drop requests whose (identifier, reqId, signature) identity
        was already seen in the dedup window."""
        out = []
        for msg, client in msgs:
            ident = (msg.get("identifier"), msg.get("reqId"),
                     msg.get("signature")) if isinstance(msg, dict) \
                else None
            if ident is not None and ident in self._seen:
                self._tm.count(TM.GATEWAY_DEDUP_HITS, 1)
                continue
            if ident is not None:
                self._seen[ident] = None
                while len(self._seen) > DEDUP_WINDOW_MAX:
                    self._seen.popitem(last=False)
            out.append((msg, client))
        return out

    # ------------------------------------------------------ pre-screen

    def screen_dispatch(self, msgs: List[Tuple[dict, str]]):
        """Phase 1 (non-blocking): every screenable signature joins one
        coalesced device dispatch. → opaque handle for
        ``screen_conclude``. Requests the gateway cannot screen (no
        single signature, unresolvable verkey) pass through unscreened
        — the nodes are the authority; the pre-screen only exists to
        keep OBVIOUS garbage off the pool's verifier."""
        items, slots = [], []
        for msg, _client in msgs:
            item = self._verify_item(msg)
            slots.append(None if item is None else len(items))
            if item is not None:
                items.append(item)
        pending = self._verifier.dispatch(items) if items else None
        return (list(msgs), slots, pending)

    def screen_ready(self, handle) -> bool:
        pending = handle[2]
        if pending is None:
            return True
        r = getattr(pending, "ready", None)
        return bool(r()) if r is not None else True

    def screen_flush(self) -> None:
        fn = getattr(self._verifier, "flush", None)
        if fn is not None:
            fn()

    def screen_conclude(self, handle) -> List[Tuple[dict, str]]:
        """Phase 2 (harvests the device): → the surviving requests;
        signature rejects are counted and dropped."""
        msgs, slots, pending = handle
        results = pending.collect() if pending is not None else []
        out = []
        traced = getattr(self.tracer, "enabled", False)
        for (msg, client), slot in zip(msgs, slots):
            if slot is not None and not results[slot]:
                self._tm.count(TM.GATEWAY_SIG_REJECTS, 1)
                continue
            if traced:
                # journey anchor: the same digest the pool keys
                # ``request_accepted`` on, so a gateway-fronted pool's
                # journeys start at the trust boundary, not the first
                # replica. Hashing is paid only when tracing is on.
                digest = _request_digest(msg)
                if digest is not None:
                    self.tracer.instant("gateway_admit", CAT_INTAKE,
                                        key=digest)
            out.append((msg, client))
        return out

    def _verify_item(self, msg) -> Optional[tuple]:
        """(signing bytes, sig64, verkey32) for a single-signature
        request dict, or None when unscreenable."""
        if not isinstance(msg, dict):
            return None
        sig = msg.get("signature")
        idr = msg.get("identifier")
        if not isinstance(sig, str) or not isinstance(idr, str) \
                or msg.get("signatures"):
            return None
        try:
            sig_raw = b58decode(sig)
            verkey = self._verkeys(idr) if self._verkeys is not None \
                else None
            vk = verkey_from_identifier(idr, verkey)
            payload = {k: v for k, v in msg.items()
                       if k not in ("signature", "signatures")}
            ser = serialize_msg_for_signing(payload)
        except Exception:
            return None
        if len(sig_raw) != 64 or len(vk) != 32:
            return None
        return (ser, sig_raw, vk)


def _request_digest(msg) -> Optional[str]:
    """The pool's request digest (Request.digest: sha256 over the
    signed state) computed from the raw dict — the join key between a
    gateway admit and the node-side journey. None when the dict cannot
    produce one (unscreenable shapes pass through undigested)."""
    if not isinstance(msg, dict):
        return None
    try:
        from plenum_tpu.common.request import Request
        return Request(**{k: msg[k] for k in (
            "identifier", "reqId", "operation", "signature",
            "signatures", "protocolVersion", "taaAcceptance",
            "endorser") if k in msg}).digest
    except (TypeError, ValueError, KeyError):
        return None
