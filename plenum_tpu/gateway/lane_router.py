"""Deterministic gateway-side conflict-lane pre-planning.

The PR-13 executor partitions each ORDERED batch into execution lanes
from the handlers' declared ``touched_keys``. The gateway runs the
same pure planner one tier earlier, on raw operation dicts it has not
parsed into ``Request`` objects yet: hot-key write traffic (many
clients hammering one NYM record) is recognized **before the pool
sees it**, so the intake can route each conflict lane's requests into
its own contiguous run of the outbound PROPAGATE envelope instead of
interleaving them — the node-side planner then rediscovers the same
partition from the same declarations and its serial spans stay dense.

Everything here is a pure function of the request list (PT012 root:
``plan_lanes`` reuse, first-appearance lane normalization, no clocks,
no set iteration) — a gateway restart, a replica of the gateway, and
the node-side planner all compute the identical routing for the same
admitted stream.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from plenum_tpu.server.execution_lanes import (
    SERIAL_LANE, LanePlan, TouchedKeys, plan_lanes)


def touched_keys_for(msg: dict) -> Optional[TouchedKeys]:
    """Declared state touches computable from a raw client request
    dict ALONE — the gateway-side mirror of
    ``WriteRequestHandler.touched_keys``. Only NYM (the only write
    type whose key set is statically declarable; NODE txns scan pool
    state and are inherently serial) resolves; anything else → None
    (serial lane), exactly the node planner's conservative answer."""
    from plenum_tpu.common.constants import NYM, TARGET_NYM
    from plenum_tpu.common.constants import DOMAIN_LEDGER_ID
    from plenum_tpu.common.state_codec import nym_to_state_key
    op = msg.get("operation")
    if not isinstance(op, dict) or op.get("type") != NYM:
        return None
    dest = op.get(TARGET_NYM)
    if not isinstance(dest, str) or not dest:
        return None
    key = nym_to_state_key(dest)
    reads = [(DOMAIN_LEDGER_ID, key)]
    idr = msg.get("identifier")
    if isinstance(idr, str) and idr:
        reads.append((DOMAIN_LEDGER_ID, nym_to_state_key(idr)))
    return TouchedKeys(reads=reads, writes=((DOMAIN_LEDGER_ID, key),))


def plan_write_lanes(msgs: Sequence[dict]) -> LanePlan:
    """Conflict-lane plan for a gateway write batch (request dicts in
    arrival order). Pure ``plan_lanes`` reuse — the identical
    union-find the executor runs on the ordered batch."""
    return plan_lanes([touched_keys_for(m) for m in msgs])


def route_by_lane(plan: LanePlan) -> List[Tuple[int, List[int]]]:
    """→ [(lane_id, [request indices])] with lanes ordered by first
    appearance in the batch and the serial lane last; indices inside a
    lane keep arrival order. This is the outbound envelope order: each
    lane's requests travel as one contiguous run."""
    by_lane: Dict[int, List[int]] = {}
    order: List[int] = []
    serial: List[int] = []
    for i, lane in enumerate(plan.lanes):
        if lane == SERIAL_LANE:
            serial.append(i)
            continue
        bucket = by_lane.get(lane)
        if bucket is None:
            bucket = by_lane[lane] = []
            order.append(lane)
        bucket.append(i)
    out = [(lane, by_lane[lane]) for lane in order]
    if serial:
        out.append((SERIAL_LANE, serial))
    return out
