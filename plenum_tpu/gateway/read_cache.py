"""Signed-read cache — proof-carrying read responses, served locally.

A GET_NYM answer from the pool carries a ``{root_hash, proof_nodes,
multi_signature}`` state proof: n-f nodes' BLS multi-signature vouches
for the root, the proof nodes tie the value to it. That makes the
RESPONSE itself the unit of trust — the gateway can replay it to any
number of clients without asking the pool again, because the proof
verifies identically in every hand (the same single-node-trust
argument as ``PoolClient.verify_proof_dict``, one tier earlier).

Freshness semantics (docs/gateway.md):

* **Verified on insert.** An entry is stored only if the injected
  ``check_proof`` (``PoolClient.check_proof_dict``) returns None; the
  named error is surfaced to the caller otherwise. The cache never
  stores — and therefore never serves — an unproven answer.
* **Window on the multi-sig timestamp.** A hit is served only while
  ``now - multi_signature.value.timestamp <= fresh_s`` — the same
  clock the proof's signers stamped, so a gateway with a skewed local
  clock fails toward the pool, not toward stale data.
* **Root pinning.** The cache tracks the newest signed root it has
  observed per ledger (the PR-7 pinned-root idea at the gateway);
  entries proven under an OLDER root are invalidated lazily on
  lookup. A pool that moved on makes the whole generation miss at
  once, which is exactly when the answers may have changed.

Capacity is LRU-bounded (``GATEWAY_CACHE_MAX``): state keys are
client-chosen, so an unbounded map is an allocation attack.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional, Tuple

from plenum_tpu.observability.telemetry import TM, NullTelemetryHub

CacheKey = Tuple[int, bytes]   # (ledger_id, state_key)


class _Entry:
    __slots__ = ("result", "root", "signed_ts")

    def __init__(self, result: dict, root: str, signed_ts: float):
        self.result = result
        self.root = root
        self.signed_ts = signed_ts


class SignedReadCache:
    def __init__(self, check_proof: Callable[..., Optional[str]],
                 fresh_s: float = None, max_entries: int = None,
                 telemetry=None):
        """``check_proof(sp, key, value, ledger_id=..., max_age=...,
        now=...) -> Optional[str]`` is ``PoolClient.check_proof_dict``
        (or a stand-in with its contract): None = proven, else the
        named failed check."""
        from plenum_tpu.common.config import Config
        self._check = check_proof
        self.fresh_s = float(Config.GATEWAY_CACHE_FRESH_S
                             if fresh_s is None else fresh_s)
        self.max_entries = int(Config.GATEWAY_CACHE_MAX
                               if max_entries is None else max_entries)
        self._entries: "OrderedDict[CacheKey, _Entry]" = OrderedDict()
        self._newest_root: dict = {}       # ledger_id -> (ts, root)
        self._tm = telemetry if telemetry is not None \
            else NullTelemetryHub()

    def __len__(self) -> int:
        return len(self._entries)

    # -------------------------------------------------------- insert

    def put(self, ledger_id: int, state_key: bytes,
            expected_value: Optional[bytes], result: dict,
            now: float) -> Optional[str]:
        """Verify + store one proof-bearing read result; → None on
        success or the named failed check (entry NOT stored)."""
        from plenum_tpu.common.constants import (
            MULTI_SIGNATURE, ROOT_HASH, STATE_PROOF)
        sp = result.get(STATE_PROOF) if isinstance(result, dict) else None
        if not isinstance(sp, dict):
            return "no state proof attached"
        err = self._check(sp, state_key, expected_value,
                          ledger_id=ledger_id, max_age=self.fresh_s,
                          now=now)
        if err is not None:
            return err
        try:
            signed_ts = float(sp[MULTI_SIGNATURE]["value"]["timestamp"])
            root = sp[ROOT_HASH]
        except (KeyError, TypeError, ValueError):
            # check_proof passed, so this shape should be impossible —
            # refuse rather than store an entry we cannot age
            return "malformed state proof: no usable timestamp/root"
        key = (int(ledger_id), bytes(state_key))
        self._entries[key] = _Entry(result, root, signed_ts)
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
        newest = self._newest_root.get(int(ledger_id))
        if newest is None or signed_ts >= newest[0]:
            self._newest_root[int(ledger_id)] = (signed_ts, root)
        return None

    # -------------------------------------------------------- lookup

    def get(self, ledger_id: int, state_key: bytes,
            now: float) -> Optional[dict]:
        """→ the cached proof-bearing result, or None (miss / stale /
        superseded root)."""
        key = (int(ledger_id), bytes(state_key))
        entry = self._entries.get(key)
        if entry is None:
            self._tm.count(TM.GATEWAY_CACHE_MISSES, 1)
            return None
        newest = self._newest_root.get(int(ledger_id))
        superseded = newest is not None and entry.root != newest[1]
        if superseded or now - entry.signed_ts > self.fresh_s:
            del self._entries[key]
            self._tm.count(TM.GATEWAY_CACHE_MISSES, 1)
            return None
        self._entries.move_to_end(key)
        self._tm.count(TM.GATEWAY_CACHE_HITS, 1)
        return entry.result
