"""Gateway tier: the batched-verify front door in front of the pool.

See docs/gateway.md. Public surface:

* :class:`~plenum_tpu.gateway.gateway.Gateway` — the glue (pump()).
* :class:`~plenum_tpu.gateway.intake.GatewayIntake` /
  :class:`~plenum_tpu.gateway.intake.SenderRegistry` — wire guard,
  dedup, batched ed25519 pre-screen.
* :class:`~plenum_tpu.gateway.admission.AdmissionController` —
  backpressure ladder (reads shed before writes).
* :class:`~plenum_tpu.gateway.read_cache.SignedReadCache` —
  proof-verified read replay keyed on BLS-signed roots.
* :mod:`~plenum_tpu.gateway.lane_router` — deterministic conflict-lane
  pre-planning for outbound write envelopes.
"""
from plenum_tpu.gateway.admission import (          # noqa: F401
    ADMIT_ALL, SHED_READS, SHED_WRITES, AdmissionController)
from plenum_tpu.gateway.gateway import (            # noqa: F401
    Gateway, GatewayTick, cache_key_for, is_read, pack_write_envelopes)
from plenum_tpu.gateway.intake import (             # noqa: F401
    GatewayIntake, SenderRegistry)
from plenum_tpu.gateway.lane_router import (        # noqa: F401
    plan_write_lanes, route_by_lane, touched_keys_for)
from plenum_tpu.gateway.read_cache import SignedReadCache  # noqa: F401
