"""Gateway admission control — backpressure from the telemetry plane.

The controller reads the PR-10 signals the pool already exports —
backlog depth (``TM.BACKLOG_DEPTH``-shaped gauge fed by the forwarder)
and the ordered-request p99 (merged ``TM.ORDERED_E2E_MS`` histograms)
— and turns them into one small state machine with three levels:

* ``ADMIT_ALL``   — both signals under their high-water marks.
* ``SHED_READS``  — either signal over its high mark: reads are
  degraded FIRST (they have a correct fallback — the signed-read
  cache still serves proof-fresh answers, and a shed read costs the
  client a retry, not durability); writes still flow.
* ``SHED_WRITES`` — either signal past its HARD mark: writes shed
  too; only cache-served reads survive. The pool drains.

Recovery is hysteretic: a level is only left when BOTH signals are
back under the LOW marks — a gauge oscillating around one mark must
not flap the decision batch to batch (the breaker-cooldown precedent,
utils/device_breaker.py).

The controller never talks to nodes: pressure arrives via
``observe(backlog, ordered_p99_ms)`` from whatever feeds the gateway
(the forwarder's in-flight accounting + the pool's merged telemetry),
so it is a pure, clock-free state machine the tests drive directly.
"""
from __future__ import annotations

from typing import Optional

ADMIT_ALL = 0
SHED_READS = 1
SHED_WRITES = 2

_LEVEL_NAMES = {ADMIT_ALL: "admit_all", SHED_READS: "shed_reads",
                SHED_WRITES: "shed_writes"}


def _cfg(config, name: str):
    from plenum_tpu.common.config import Config
    return getattr(config, name, getattr(Config, name))


class AdmissionController:
    """Three-level shed ladder with per-signal hysteresis."""

    def __init__(self, config=None):
        self.backlog_high = float(_cfg(config, "GATEWAY_BACKLOG_HIGH"))
        self.backlog_low = float(_cfg(config, "GATEWAY_BACKLOG_LOW"))
        self.backlog_hard = float(_cfg(config, "GATEWAY_BACKLOG_HARD"))
        self.p99_high = float(_cfg(config, "GATEWAY_P99_HIGH_MS"))
        self.p99_low = float(_cfg(config, "GATEWAY_P99_LOW_MS"))
        self.p99_hard = float(_cfg(config, "GATEWAY_P99_HARD_MS"))
        self.level = ADMIT_ALL
        self._backlog = 0.0
        self._p99: Optional[float] = None

    # ------------------------------------------------------- pressure

    def observe(self, backlog: float,
                ordered_p99_ms: Optional[float]) -> int:
        """Feed the current pressure signals; → the (possibly new)
        level. Escalation is immediate; de-escalation steps one level
        at a time and only when BOTH signals sit under the low marks."""
        self._backlog = float(backlog)
        self._p99 = ordered_p99_ms
        p99 = ordered_p99_ms if ordered_p99_ms is not None else 0.0
        if self._backlog >= self.backlog_hard or p99 >= self.p99_hard:
            self.level = SHED_WRITES
        elif self._backlog >= self.backlog_high or p99 >= self.p99_high:
            self.level = max(self.level, SHED_READS)
        elif self._backlog < self.backlog_low and p99 < self.p99_low:
            if self.level > ADMIT_ALL:
                self.level -= 1
        return self.level

    # ------------------------------------------------------- verdicts

    def admits_read(self) -> bool:
        """Forwarded (cache-missing) reads survive only below
        SHED_READS; cache HITS are always served — they cost the pool
        nothing and carry their own proof of correctness."""
        return self.level < SHED_READS

    def admits_write(self) -> bool:
        return self.level < SHED_WRITES

    def level_name(self) -> str:
        return _LEVEL_NAMES[self.level]

    def snapshot(self) -> dict:
        return {"level": self.level_name(),
                "backlog": self._backlog,
                "ordered_p99_ms": self._p99}
