"""The gateway proper — glue from intake to pool.

One ``pump()`` per service tick takes the tick's raw client envelopes
and runs the full front-door pipeline:

    admission.observe ──┐
    unpack_client ──────┤  (wire guard: strikes/shedding per sender)
    fresh_only ─────────┤  (dedup before any signature work)
    split reads/writes ─┤
    reads:  cache → serve → put   (shed FIRST under pressure;
                                   cache hits always served)
    writes: screen → lane-route → pack → forward
                                  (shed only past the HARD marks)

The gateway owns no consensus state and holds no keys the pool trusts:
``forward_writes`` delivers packed PROPAGATE envelopes to nodes that
re-authenticate everything (``Node.process_gateway_envelope``), and
``serve_read`` returns proof-bearing results the cache re-verifies
before storing. A compromised gateway can therefore deny service but
never forge admission or serve an unproven read.

Time is injected (``now`` plus per-envelope arrival stamps) — the
gateway is clock-free and deterministic for a given arrival schedule,
which is what lets the bench drive it open-loop on a mock timer.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import msgpack

from plenum_tpu.common.serializers import flat_wire
from plenum_tpu.gateway.admission import AdmissionController
from plenum_tpu.gateway.intake import GatewayIntake, SenderRegistry
from plenum_tpu.gateway.lane_router import plan_write_lanes, route_by_lane
from plenum_tpu.gateway.read_cache import SignedReadCache
from plenum_tpu.observability.telemetry import TM, NullTelemetryHub

# read op types the gateway recognizes (mirrors the pool's registered
# ReadRequestHandlers; anything unrecognized is treated as a write and
# settled by the pool's own validation)
GET_NYM_TYPE = "105"
READ_TYPES = frozenset({GET_NYM_TYPE, "3", "6", "7", "10"})

_UNCACHEABLE = object()   # leaf_value_for failed: serve, don't cache


class _Rec:
    """One in-flight request: the parsed dict plus the routing facts
    the intake treats as opaque (client id, arrival stamp)."""
    __slots__ = ("client", "arrived")

    def __init__(self, client: str, arrived: float):
        self.client = client
        self.arrived = arrived


class GatewayTick:
    """What one pump() did — counts plus the admitted/answered work,
    so tests can replay the admitted stream against a gateway-less
    pool and assert byte-equal roots."""

    def __init__(self):
        self.admitted_writes: List[Tuple[dict, str]] = []
        self.replies: List[Tuple[str, dict]] = []
        self.shed_reads = 0
        self.shed_writes = 0
        self.cache_hits = 0
        self.sig_rejects = 0
        self.level = "admit_all"


def is_read(msg: dict) -> bool:
    op = msg.get("operation") if isinstance(msg, dict) else None
    return isinstance(op, dict) and op.get("type") in READ_TYPES


def cache_key_for(msg: dict) -> Optional[Tuple[int, bytes]]:
    """(ledger_id, state_key) for reads the cache can serve: current-
    state GET_NYM only. Timestamped (state-at-a-time) reads bypass the
    cache — their answer depends on the asked-for time, not the newest
    signed root."""
    from plenum_tpu.common.constants import DOMAIN_LEDGER_ID, TARGET_NYM
    from plenum_tpu.common.state_codec import nym_to_state_key
    op = msg.get("operation")
    if not isinstance(op, dict) or op.get("type") != GET_NYM_TYPE \
            or op.get("timestamp") is not None:
        return None
    dest = op.get(TARGET_NYM)
    if not isinstance(dest, str) or not dest:
        return None
    return (DOMAIN_LEDGER_ID, nym_to_state_key(dest))


def leaf_value_for(result: dict) -> Optional[bytes]:
    """The state-trie leaf bytes a GET_NYM result claims — the same
    (data, seqNo, txnTime) re-encode the client does before checking
    the proof, so the cache verifies the value it will later serve.
    None = the result claims absence."""
    from plenum_tpu.common.state_codec import encode_state_value
    if result.get("data") is None:
        return None
    return encode_state_value(result["data"], result.get("seqNo"),
                              result.get("txnTime"))


def pack_write_envelopes(admitted: List[Tuple[dict, "_Rec"]],
                         lane_order: List[Tuple[int, List[int]]]
                         ) -> bytes:
    """One PROPAGATE FLAT_WIRE envelope with each conflict lane's
    requests as a contiguous run (serial lane last) — the gateway→node
    wire format."""
    raw: List[bytes] = []
    names: List[str] = []
    for _lane, idxs in lane_order:
        for i in idxs:
            msg, rec = admitted[i]
            raw.append(msgpack.packb(msg, use_bin_type=True))
            names.append(rec.client)
    return flat_wire.encode_propagate_envelope(raw, names)


class Gateway:
    def __init__(self, forward_writes: Callable[[bytes], None],
                 serve_read: Callable[[dict, str], Optional[dict]] = None,
                 check_proof=None, verifier=None, verkey_provider=None,
                 config=None, telemetry=None, pool_hubs=None,
                 tracer=None):
        """``forward_writes(envelope_bytes)`` delivers a packed write
        envelope to the pool; ``serve_read(msg, client)`` performs one
        pool read and returns the proof-bearing result dict (None =
        unavailable); ``check_proof`` is ``PoolClient.check_proof_dict``
        (enables the signed-read cache when given). ``pool_hubs`` is an
        iterable of pool TelemetryHubs — or a callable returning one —
        that ``pump()`` self-sources pressure from when the driver does
        not measure backlog/p99 itself (defaults to the gateway's own
        hub)."""
        self._tm = telemetry if telemetry is not None \
            else NullTelemetryHub()
        self._pool_hubs = pool_hubs
        self.intake = GatewayIntake(
            verifier=verifier, verkey_provider=verkey_provider,
            senders=SenderRegistry(telemetry=self._tm),
            telemetry=self._tm, tracer=tracer)
        self.admission = AdmissionController(config)
        self.cache = SignedReadCache(check_proof, telemetry=self._tm) \
            if check_proof is not None else None
        self._forward = forward_writes
        self._serve_read = serve_read

    # ---------------------------------------------------- service tick

    def pump(self, arrivals: List[Tuple[bytes, str, float]], now: float,
             backlog: Optional[float] = None,
             pool_p99_ms: Optional[float] = None) -> GatewayTick:
        """Serve one tick's arrivals ``[(envelope bytes, sender,
        arrival time)]`` under the current pool pressure. A driver that
        measures pressure itself passes ``backlog``/``pool_p99_ms``;
        left None, each is read live from the pool hubs (newest
        ``TM.BACKLOG_DEPTH`` sample, p99 of the merged
        ``TM.ORDERED_E2E_MS`` histograms). Never raises on
        sender-controlled input."""
        tick = GatewayTick()
        if backlog is None or pool_p99_ms is None:
            live_backlog, live_p99 = self._live_pressure()
            if backlog is None:
                backlog = live_backlog
            if pool_p99_ms is None:
                pool_p99_ms = live_p99
        self.admission.observe(backlog, pool_p99_ms)
        tick.level = self.admission.level_name()
        self._tm.gauge(TM.GATEWAY_BACKLOG, backlog)

        work: List[Tuple[dict, _Rec]] = []
        for data, sender, arrived in arrivals:
            unpacked = self.intake.unpack_client(data, sender)
            if not unpacked:
                continue
            for msg, client in unpacked:
                work.append((msg, _Rec(client, arrived)))
        work = self.intake.fresh_only(work)

        pending_reads = [w for w in work if is_read(w[0])]
        pending_writes = [w for w in work if not is_read(w[0])]
        self._serve_reads(pending_reads, now, tick)
        self._admit_writes(pending_writes, now, tick)
        return tick

    # ----------------------------------------------------------- reads

    def _serve_reads(self, pending: List[Tuple[dict, "_Rec"]],
                     now: float, tick: GatewayTick) -> None:
        for msg, rec in pending:
            key = cache_key_for(msg)
            if key is not None and self.cache is not None:
                hit = self.cache.get(key[0], key[1], now)
                if hit is not None:
                    # always served, whatever the shed level: a cache
                    # hit costs the pool nothing and carries its proof
                    tick.replies.append((rec.client, hit))
                    tick.cache_hits += 1
                    self._mark_done(rec, now)
                    continue
            if not self.admission.admits_read():
                self._tm.count(TM.GATEWAY_SHED_READS, 1)
                tick.shed_reads += 1
                self._mark_done(rec, now)
                continue
            result = self._serve_read(msg, rec.client) \
                if self._serve_read is not None else None
            if result is not None:
                if key is not None and self.cache is not None:
                    try:
                        value = leaf_value_for(result)
                    except (KeyError, TypeError, ValueError):
                        value = _UNCACHEABLE
                    if value is not _UNCACHEABLE:
                        self.cache.put(key[0], key[1], value, result,
                                       now)
                tick.replies.append((rec.client, result))
            self._mark_done(rec, now)

    # ---------------------------------------------------------- writes

    def _admit_writes(self, pending: List[Tuple[dict, "_Rec"]],
                      now: float, tick: GatewayTick) -> None:
        if not pending:
            return
        if not self.admission.admits_write():
            self._tm.count(TM.GATEWAY_SHED_WRITES, len(pending))
            tick.shed_writes += len(pending)
            for _msg, rec in pending:
                self._mark_done(rec, now)
            return
        n_before = len(pending)
        handle = self.intake.screen_dispatch(pending)
        self.intake.screen_flush()
        admitted = self.intake.screen_conclude(handle)
        tick.sig_rejects = n_before - len(admitted)
        for _msg, rec in pending:
            self._mark_done(rec, now)
        if not admitted:
            return
        plan = plan_write_lanes([msg for msg, _ in admitted])
        self._tm.observe(TM.GATEWAY_LANES_PER_BATCH, plan.n_lanes)
        env = pack_write_envelopes(admitted, route_by_lane(plan))
        self._forward(env)
        self._tm.count(TM.GATEWAY_ADMITTED, len(admitted))
        tick.admitted_writes.extend(
            (msg, rec.client) for msg, rec in admitted)

    # ------------------------------------------------------- telemetry

    def _live_pressure(self) -> Tuple[float, Optional[float]]:
        """(backlog, ordered_p99_ms) read from the live pool hubs with
        the same merge semantics ``merged_snapshot`` applies: the
        newest ``BACKLOG_DEPTH`` gauge sample wins, ``ORDERED_E2E_MS``
        histograms add before the quantile. No hub has recorded either
        → (0.0, None), the pre-pressure defaults."""
        from plenum_tpu.observability.telemetry import LogLinearHistogram
        hubs = self._pool_hubs() if callable(self._pool_hubs) \
            else self._pool_hubs
        if not hubs:
            hubs = (self._tm,)
        backlog_ts, backlog = None, 0.0
        scratch = None
        for hub in hubs:
            s = hub.gauge_sample(TM.BACKLOG_DEPTH)
            if s is not None and (backlog_ts is None or s[0] >= backlog_ts):
                backlog_ts, backlog = s
            h = hub.histogram(TM.ORDERED_E2E_MS)
            if h is not None:
                if scratch is None:
                    scratch = LogLinearHistogram()
                scratch.merge(h)
        p99 = scratch.quantile(0.99) if scratch is not None else None
        return float(backlog), p99

    def _mark_done(self, rec: "_Rec", now: float) -> None:
        self._tm.observe(TM.GATEWAY_E2E_MS,
                         max(0.0, (now - rec.arrived) * 1000.0))
