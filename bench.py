#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Current headline (BASELINE.json north star path): batched ed25519
signature verification throughput per chip — the hot operation under
ordered write-requests/sec (every client write costs >= 1 sig verify, and
the reference's CPU pool baselines at <1k req/s). vs_baseline is the
speedup over the scalar verification floor measured on this host.

Once the consensus pool lands, this will switch to ordered write-reqs/sec
on a 4-node in-process pool with TPU-batched verification.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# persistent compilation cache: first compile of the verify kernel is
# tens of seconds; subsequent runs hit the cache
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))

BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
UNIQUE = 256


def main():
    import numpy as np
    from plenum_tpu.crypto import ed25519 as ed
    from plenum_tpu.crypto.fixtures import make_signed_batch
    from plenum_tpu.ops import ed25519_jax as edj

    msgs, sigs, vks = make_signed_batch(BATCH, seed=42, unique=UNIQUE,
                                        msg_prefix=b"bench-req")

    # warmup (compile)
    ok = edj.verify_batch(msgs[:BATCH], sigs[:BATCH], vks[:BATCH])
    assert bool(np.all(ok)), "benchmark signatures failed to verify"

    runs = 3
    t0 = time.perf_counter()
    for _ in range(runs):
        edj.verify_batch(msgs, sigs, vks)
    dt = (time.perf_counter() - t0) / runs
    device_rate = BATCH / dt

    # scalar floor on this host (pure-Python RFC 8032)
    n_scalar = 30
    t0 = time.perf_counter()
    for i in range(n_scalar):
        ed.verify(msgs[i], sigs[i], vks[i])
    scalar_rate = n_scalar / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "ed25519 batch verify throughput per chip (batch=%d)" % BATCH,
        "value": round(device_rate, 1),
        "unit": "sigs/s",
        "vs_baseline": round(device_rate / scalar_rate, 2),
    }))


if __name__ == "__main__":
    main()
